"""Collective micro-benchmarks — the ``ds_bench`` /
``benchmarks/communication/*`` analog: sweep message sizes over
all_reduce / all_gather / reduce_scatter / all_to_all / ppermute on the
live device set and report algorithmic bandwidth. On the virtual CPU mesh
the numbers are meaningless but the sweep validates every collective
lowers and runs; on real slices it measures ICI.
"""
from __future__ import annotations

import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
               "ppermute")


def _op(name: str, axis: str, n: int):
    """Dispatch through the project's comm facade (the reference ds_bench
    measures through deepspeed.comm, not the raw backend)."""
    from deepspeed_tpu.comm import comm as C
    if name == "all_reduce":
        return lambda x: C.all_reduce(x, axis_name=axis)
    if name == "all_gather":
        return lambda x: C.all_gather(x, axis_name=axis)
    if name == "reduce_scatter":
        return lambda x: C.reduce_scatter(x, axis_name=axis)
    if name == "all_to_all":
        return lambda x: C.all_to_all(x.reshape(n, -1), axis_name=axis,
                                      split_axis=0,
                                      concat_axis=0).reshape(-1)
    if name == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]
        return lambda x: C.ppermute(x, perm, axis_name=axis)
    raise ValueError(name)


def _bus_bytes(name: str, per_device_bytes: int, n: int) -> float:
    """Algorithmic bus bytes PER DEVICE from the per-device message size
    (ring conventions, the reference's bandwidth formulas)."""
    if name == "all_reduce":
        return 2 * per_device_bytes * (n - 1) / n
    if name in ("all_gather", "reduce_scatter"):
        return per_device_bytes * (n - 1) / n
    if name == "all_to_all":
        return per_device_bytes * (n - 1) / n
    return per_device_bytes  # ppermute: one hop


def run_sweep(sizes_mb=(1, 4, 16), trials: int = 5,
              collectives=COLLECTIVES, axis: str = "data",
              mesh: Mesh = None) -> List[Dict]:
    devs = jax.devices()
    n = len(devs)
    mesh = mesh or Mesh(np.asarray(devs), (axis,))
    results = []
    sync = jax.jit(lambda y: jnp.sum(y.reshape(-1)[:1]))
    for name in collectives:
        for mb in sizes_mb:
            elems = int(mb * (1 << 20)) // 4
            # per-device shards must themselves split n ways for
            # reduce_scatter/all_to_all → global size a multiple of n^2
            per_dev = max(n * n, elems // (n * n) * (n * n))
            x = jnp.ones((per_dev,), jnp.float32)
            fn = jax.jit(jax.shard_map(
                _op(name, axis, n), mesh=mesh, in_specs=P(axis),
                out_specs=P(axis) if name != "all_gather" else P(),
                check_vma=False))
            # warm up BOTH programs (through remote relays
            # block_until_ready alone can return early — the host
            # transfer in sync() is the reliable barrier)
            float(sync(fn(x)))
            t0 = time.perf_counter()
            for _ in range(trials):
                y = fn(x)
            float(sync(y))
            dt = (time.perf_counter() - t0) / trials
            nbytes = per_dev // n * 4  # per-device payload
            busbw = _bus_bytes(name, nbytes, n) / max(dt, 1e-9)
            results.append({
                "collective": name, "size_mb": mb, "devices": n,
                "latency_ms": round(dt * 1e3, 3),
                "busbw_GiBps": round(busbw / (1 << 30), 3)})
    return results


def main() -> None:
    import argparse
    import json
    ap = argparse.ArgumentParser(description="collective bandwidth sweep")
    ap.add_argument("--sizes-mb", default="1,4,16")
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--collectives", default=",".join(COLLECTIVES))
    args = ap.parse_args()
    out = run_sweep(tuple(float(s) for s in args.sizes_mb.split(",")),
                    args.trials, tuple(args.collectives.split(",")))
    for r in out:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
