"""Collective micro-benchmarks — the ``ds_bench`` /
``benchmarks/communication/*`` analog: sweep message sizes over
all_reduce / all_gather / reduce_scatter / all_to_all / ppermute on the
live device set and report algorithmic bandwidth. On the virtual CPU mesh
the numbers are meaningless but the sweep validates every collective
lowers and runs; on real slices it measures ICI.
"""
from __future__ import annotations

import functools
import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
               "ppermute")


def _op(name: str, axis: str, n: int):
    if name == "all_reduce":
        return lambda x: jax.lax.psum(x, axis)
    if name == "all_gather":
        return lambda x: jax.lax.all_gather(x, axis)
    if name == "reduce_scatter":
        return lambda x: jax.lax.psum_scatter(x, axis, tiled=True)
    if name == "all_to_all":
        return lambda x: jax.lax.all_to_all(
            x.reshape(n, -1), axis, split_axis=0, concat_axis=0,
            tiled=False).reshape(-1)
    if name == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]
        return lambda x: jax.lax.ppermute(x, axis, perm)
    raise ValueError(name)


def _bus_bytes(name: str, nbytes: int, n: int) -> float:
    """Algorithmic bus bytes per device (ring conventions, as the
    reference's bandwidth formulas)."""
    if name == "all_reduce":
        return 2 * nbytes * (n - 1) / n
    if name in ("all_gather", "reduce_scatter"):
        return nbytes * (n - 1) / n
    if name == "all_to_all":
        return nbytes * (n - 1) / n
    return nbytes  # ppermute: one hop


def run_sweep(sizes_mb=(1, 4, 16), trials: int = 5,
              collectives=COLLECTIVES, axis: str = "data",
              mesh: Mesh = None) -> List[Dict]:
    devs = jax.devices()
    n = len(devs)
    mesh = mesh or Mesh(np.asarray(devs), (axis,))
    results = []
    for name in collectives:
        for mb in sizes_mb:
            elems = int(mb * (1 << 20)) // 4
            per_dev = max(n, elems // n * n)  # divisible local chunks
            x = jnp.ones((per_dev,), jnp.float32)
            fn = jax.jit(jax.shard_map(
                _op(name, axis, n), mesh=mesh, in_specs=P(axis),
                out_specs=P(axis) if name != "all_gather" else P(),
                check_vma=False))
            y = fn(x)
            jax.block_until_ready(y)
            t0 = time.perf_counter()
            for _ in range(trials):
                y = fn(x)
            jax.block_until_ready(y)
            float(jnp.sum(y.reshape(-1)[:1]))  # relay-safe sync
            dt = (time.perf_counter() - t0) / trials
            nbytes = per_dev // n * 4  # per-device payload
            busbw = _bus_bytes(name, nbytes * n, n) / max(dt, 1e-9)
            results.append({
                "collective": name, "size_mb": mb, "devices": n,
                "latency_ms": round(dt * 1e3, 3),
                "busbw_gbps": round(busbw / (1 << 30), 3)})
    return results


def main() -> None:
    import argparse
    import json
    ap = argparse.ArgumentParser(description="collective bandwidth sweep")
    ap.add_argument("--sizes-mb", default="1,4,16")
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--collectives", default=",".join(COLLECTIVES))
    args = ap.parse_args()
    out = run_sweep(tuple(float(s) for s in args.sizes_mb.split(",")),
                    args.trials, tuple(args.collectives.split(",")))
    for r in out:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
