"""True int8×int8 GEMM (w8a8) for inference.

The module_inject int8 path stores weights as {"q": int8, "scale": f32}
and dequantizes into a bf16 matmul — a memory win only. This op closes
the compute half: the v5e MXU multiplies int8×int8 at twice the bf16
rate, so the GEMM itself runs on int8 operands:

    y = x @ (q * s)  with per-row scales s[k]
      = sum_k (x[k] * s[k]) * q[k, j]          — fold s into the activation
      ≈ sz * sum_k z_q[k] * q[k, j]            — one dynamic per-row quant

Folding the weight's per-row scales into the activation BEFORE the
dynamic activation quant makes the int8 dot exact up to ONE activation
rounding — no per-group partial dots needed. ``preferred_element_type=
int32`` keeps the accumulator exact; the single fp rescale happens on the
[..., N] output.

Scope: the MLP in/out GEMMs (the decode-FLOPs majority). 3-D attention
projections keep the dequant-bf16 path — their scale grid spans output
heads and is not foldable on either side — and the tied LM head is the
(never-quantized) embedding table.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w


def int8_matmul(x: jax.Array, qw: dict, out_dtype=None) -> jax.Array:
    """``x [..., K] @ {"q": int8 [K, N], "scale": f32 [K, 1]}`` with the
    int8 contraction on the MXU."""
    q = qw["q"]
    if q.ndim != 2:
        raise ValueError(f"int8_matmul handles 2-D weights, got "
                         f"{q.shape} (attention projections keep the "
                         "dequant path)")
    out_dtype = out_dtype or x.dtype
    scale = qw["scale"].astype(jnp.float32).reshape(q.shape[0])   # [K]
    z = x.astype(jnp.float32) * scale                             # fold
    amax = jnp.max(jnp.abs(z), axis=-1, keepdims=True)
    sz = jnp.where(amax > 0, amax / 127.0, 1.0)
    zq = jnp.clip(jnp.round(z / sz), -127, 127).astype(jnp.int8)
    y = jax.lax.dot_general(
        zq, q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (y.astype(jnp.float32) * sz).astype(out_dtype)


def _squeeze_leading_ones(shape):
    out = list(shape)
    while len(out) > 1 and out[0] == 1:
        out.pop(0)
    return tuple(out)


def int8_einsum(subscripts: str, x: jax.Array, qw: dict,
                x_contract_ndim: int, w_out_ndim: int,
                out_dtype) -> jax.Array:
    """General w8a8 einsum for {"q": int8, "oscale"} leaves (per-output-
    channel scales, quantize.py quantize_weight_out): one dynamic
    per-token activation quant, int8×int8 dot on the MXU (int32
    accumulator), one fp rescale of the output:

        y = einsum(x, q·s_out) = einsum(x_q, q) · s_x · s_out

    ``x_contract_ndim``: trailing dims of x the einsum contracts (1 for
    [...,E]·[E,H,D]; 2 for [...,H,D]·[H,D,E]). ``w_out_ndim``: output
    dims the weight contributes (sizes the rescale broadcast)."""
    q, s = qw["q"], qw["oscale"]
    xf = x.astype(jnp.float32)
    red = tuple(range(x.ndim - x_contract_ndim, x.ndim))
    amax = jnp.max(jnp.abs(xf), axis=red)
    sx = jnp.where(amax > 0, amax / 127.0, 1.0)
    sx_in = sx.reshape(sx.shape + (1,) * x_contract_ndim)
    xq = jnp.clip(jnp.round(xf / sx_in), -127, 127).astype(jnp.int8)
    y = jnp.einsum(subscripts, xq, q,
                   preferred_element_type=jnp.int32)
    # oscale carries 1s on the weight's contraction dims; squeeze the
    # LEADING 1s so right-aligned broadcasting matches the output layout
    # ([1,H,D]->[H,D] vs [...,H,D]; [X,1,F] stays, batching over X)
    s = s.reshape(_squeeze_leading_ones(s.shape))
    sx_out = sx.reshape(sx.shape + (1,) * w_out_ndim)
    return (y.astype(jnp.float32) * sx_out
            * s.astype(jnp.float32)).astype(out_dtype)


def maybe_int8_einsum(subscripts: str, x: jax.Array, w: Any, dtype,
                      int8_compute: bool, x_contract_ndim: int,
                      w_out_ndim: int) -> jax.Array:
    """Attention/expert projection seam: true-int8 einsum for oscale
    leaves under w8a8; dequant einsum otherwise."""
    if int8_compute and is_quantized(w) and "oscale" in w:
        return int8_einsum(subscripts, x, w, x_contract_ndim,
                           w_out_ndim, dtype)
    from deepspeed_tpu.model_implementations.transformer import _w
    return jnp.einsum(subscripts, x, _w(w, dtype)).astype(dtype)


def maybe_int8_matmul(x: jax.Array, w: Any, dtype,
                      int8_compute: bool) -> jax.Array:
    """The fused transformer's 2-D GEMM seam: int8 dot when the leaf is
    quantized and the config opts in; bf16 dequant-matmul otherwise."""
    if int8_compute and is_quantized(w):
        if "oscale" in w:
            return int8_einsum("...k,kn->...n", x, w, 1, 1, dtype)
        if w["q"].ndim == 2:
            return int8_matmul(x, w, out_dtype=dtype)
    from deepspeed_tpu.model_implementations.transformer import _w
    return (x @ _w(w, dtype)).astype(dtype)
