"""1-bit Adam / 0-1 Adam style optimizers.

Analog of ``runtime/fp16/onebit/{adam,zoadam}.py``: exact Adam during a
warmup of ``freeze_step`` steps; afterwards the second moment is FROZEN
and only the (compressible) momentum is synchronized — with error-feedback
sign compression from deepspeed_tpu.comm.compressed when running inside a
``shard_map`` with per-worker gradients.

Two usage modes:
* engine mode (``axis_name=None``): gradients arrive already averaged
  (GSPMD inserted the reduction); the optimizer still applies the
  freeze-variance schedule — the convergence behavior of 1-bit Adam
  without the wire format.
* comm mode (``axis_name='data'`` under shard_map): grads are LOCAL;
  warmup averages them exactly (pmean), the compression stage averages
  sign-compressed momentum — the full reference algorithm.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from deepspeed_tpu.comm.compressed import (compressed_allreduce_tree,
                                           init_error_feedback)
from deepspeed_tpu.ops.adam import Optimizer, _tree_zeros_like


@struct.dataclass
class OnebitAdamState:
    count: jnp.ndarray
    mu: any
    nu: any
    worker_error: any
    server_error: any


def onebit_adam(betas=(0.9, 0.999), eps: float = 1e-8,
                weight_decay: float = 0.0, freeze_step: int = 100,
                axis_name: Optional[str] = None,
                cuda_aware: bool = False, comm_backend_name: str = "xla",
                **_) -> Optimizer:
    b1, b2 = betas

    def init(params):
        w_err, s_err = init_error_feedback(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))
        return OnebitAdamState(count=jnp.zeros((), jnp.int32),
                               mu=_tree_zeros_like(params),
                               nu=_tree_zeros_like(params),
                               worker_error=w_err, server_error=s_err)

    def update(grads, state, params, lr):
        count = state.count + 1
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        frozen = count > freeze_step

        def warmup_stage(op):
            g, st = op
            if axis_name is not None:
                g = jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), g)
            mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x, st.mu, g)
            nu = jax.tree.map(lambda v, x: b2 * v + (1 - b2) * x * x,
                              st.nu, g)
            return mu, nu, st.worker_error, st.server_error

        def frozen_stage(op):
            g, st = op
            mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x, st.mu, g)
            if axis_name is not None:
                mu, w_err, s_err = compressed_allreduce_tree(
                    mu, st.worker_error, st.server_error, axis_name)
            else:
                w_err, s_err = st.worker_error, st.server_error
            return mu, st.nu, w_err, s_err   # variance frozen

        mu, nu, w_err, s_err = jax.lax.cond(frozen, frozen_stage,
                                            warmup_stage, (grads, state))
        # bias corrections pin at the freeze boundary: nu is frozen, so a
        # still-growing bc2 would silently raise the effective lr ~3x
        # (the reference drops corrections in the compression stage)
        cf = jnp.minimum(count, freeze_step).astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf
        bc2 = 1.0 - b2 ** cf

        def leaf(m, v, p):
            upd = -lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay > 0.0:
                upd = upd - lr * weight_decay * p
            return upd.astype(p.dtype)

        updates = jax.tree.map(leaf, mu, nu, params)
        return updates, OnebitAdamState(count=count, mu=mu, nu=nu,
                                        worker_error=w_err,
                                        server_error=s_err)

    return Optimizer(init=init, update=update)
