"""The 1-bit optimizer family: OnebitAdam, 0/1 Adam, OnebitLamb.

Analog of ``runtime/fp16/onebit/{adam,zoadam,lamb}.py``: exact optimization
during a warmup phase; afterwards the second moment is FROZEN and only the
(compressible) momentum — or, for 0/1 Adam, an update accumulator on an
exponentially-sparsifying schedule — is synchronized, with error-feedback
sign compression from ``deepspeed_tpu.comm.compressed``.

Two usage modes:
* engine mode (``axis_name=None``): gradients arrive already averaged
  (GSPMD inserted the reduction); the optimizers still apply their
  freeze/local-step schedules — the convergence behavior without the wire
  format.
* comm mode (``axis_name=('data',...)`` under shard_map): grads are LOCAL;
  warmup averages them exactly (pmean), the compression stage exchanges
  sign-compressed state — the full reference algorithm on the wire. The
  engine enters this mode automatically for pure-DP meshes
  (``DeepSpeedEngine._make_compressed_step_fn``).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from deepspeed_tpu.comm.compressed import (compressed_allreduce_tree,
                                           init_error_feedback)
from deepspeed_tpu.ops.adam import Optimizer, _tree_zeros_like


@struct.dataclass
class OnebitAdamState:
    count: jnp.ndarray
    mu: any
    nu: any
    worker_error: any
    server_error: any


def _check_reference_extras(amsgrad=False, max_grad_norm=0.0,
                            eps_inside_sqrt=False):
    """Reference-JSON compatibility: these keys are legal in upstream
    onebit configs; accept the supported values, refuse the rest loudly
    (the reference itself rejects amsgrad)."""
    if amsgrad:
        raise ValueError("amsgrad is not supported by the 1-bit optimizer "
                         "family (same restriction as the reference)")
    if max_grad_norm:
        raise NotImplementedError(
            "max_grad_norm inside the optimizer is not supported; use the "
            "engine's gradient_clipping config instead")
    if eps_inside_sqrt:
        raise NotImplementedError("eps_inside_sqrt=True is not supported")


def onebit_adam(betas=(0.9, 0.999), eps: float = 1e-8,
                weight_decay: float = 0.0, freeze_step: int = 100,
                axis_name: Optional[str] = None,
                bias_correction: bool = True,
                amsgrad: bool = False, max_grad_norm: float = 0.0,
                eps_inside_sqrt: bool = False,
                cuda_aware: bool = False,
                comm_backend_name: str = "xla") -> Optimizer:
    b1, b2 = betas
    _check_reference_extras(amsgrad, max_grad_norm, eps_inside_sqrt)
    if not bias_correction:
        raise NotImplementedError(
            "onebit_adam always applies bias correction (pinned at the "
            "freeze boundary); bias_correction=False is not supported")

    def init(params):
        w_err, s_err = init_error_feedback(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))
        return OnebitAdamState(count=jnp.zeros((), jnp.int32),
                               mu=_tree_zeros_like(params),
                               nu=_tree_zeros_like(params),
                               worker_error=w_err, server_error=s_err)

    def update(grads, state, params, lr):
        count = state.count + 1
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        frozen = count > freeze_step

        def warmup_stage(op):
            g, st = op
            if axis_name is not None:
                g = jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), g)
            mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x, st.mu, g)
            nu = jax.tree.map(lambda v, x: b2 * v + (1 - b2) * x * x,
                              st.nu, g)
            return mu, nu, st.worker_error, st.server_error

        def frozen_stage(op):
            g, st = op
            mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x, st.mu, g)
            if axis_name is not None:
                mu, w_err, s_err = compressed_allreduce_tree(
                    mu, st.worker_error, st.server_error, axis_name)
            else:
                w_err, s_err = st.worker_error, st.server_error
            return mu, st.nu, w_err, s_err   # variance frozen

        mu, nu, w_err, s_err = jax.lax.cond(frozen, frozen_stage,
                                            warmup_stage, (grads, state))
        # bias corrections pin at the freeze boundary: nu is frozen, so a
        # still-growing bc2 would silently raise the effective lr ~3x
        # (the reference drops corrections in the compression stage)
        cf = jnp.minimum(count, freeze_step).astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf
        bc2 = 1.0 - b2 ** cf

        def leaf(m, v, p):
            upd = -lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay > 0.0:
                upd = upd - lr * weight_decay * p
            return upd.astype(p.dtype)

        updates = jax.tree.map(leaf, mu, nu, params)
        return updates, OnebitAdamState(count=count, mu=mu, nu=nu,
                                        worker_error=w_err,
                                        server_error=s_err)

    return Optimizer(init=init, update=update)


@struct.dataclass
class ZeroOneAdamState:
    count: jnp.ndarray
    mu: any
    nu: any
    accum: any                 # u in the paper: sum of applied local deltas
    lrs: jnp.ndarray           # accumulated lr over the local-step window
    var_interval: jnp.ndarray  # current variance-update interval (doubles)
    var_counter: jnp.ndarray
    local_interval: jnp.ndarray
    local_counter: jnp.ndarray
    worker_error: any
    server_error: any


def zero_one_adam(betas=(0.9, 0.999), eps: float = 1e-8,
                  weight_decay: float = 0.0,
                  var_freeze_step: int = 100000,
                  var_update_scaler: int = 16,
                  local_step_scaler: int = 32678,
                  local_step_clipper: int = 16,
                  axis_name: Optional[str] = None,
                  bias_correction: bool = True,
                  amsgrad: bool = False, max_grad_norm: float = 0.0,
                  eps_inside_sqrt: bool = False,
                  cuda_aware: bool = False,
                  comm_backend_name: str = "xla") -> Optimizer:
    """0/1 Adam (arXiv:2202.06009; reference runtime/fp16/onebit/zoadam.py).

    Two phases, switching at ``var_freeze_step``:

    * **Adaptive-variance phase**: the second moment (and an exact-gradient
      momentum update) refresh only every ``var_interval`` steps, and that
      interval doubles after every ``var_update_scaler`` refreshes (the
      paper's kappa). Between refreshes, the momentum advances with the
      1-bit error-feedback-compressed gradient exchange.
    * **Local-step phase** (variance frozen): momentum advances with the
      purely LOCAL gradient — no communication at all — while an
      accumulator records the applied updates. Every ``local_interval``
      steps the local updates are rolled back, the accumulator is
      1-bit-allreduced, the synced update is applied and the momentum is
      re-seeded from it; the interval doubles every ``local_step_scaler``
      syncs up to ``local_step_clipper``. This is the 0/1 in the name:
      most steps exchange 0 bits.

    No bias correction regardless of ``bias_correction`` (the reference
    zoadam update rule applies none either). In engine mode
    (``axis_name=None``) the exchanges are identity (gradients arrive
    pre-reduced); under ``shard_map`` with per-worker grads the wire
    behavior is exact.
    """
    b1, b2 = betas
    _check_reference_extras(amsgrad, max_grad_norm, eps_inside_sqrt)

    def init(params):
        zeros = _tree_zeros_like(params)
        w_err, s_err = init_error_feedback(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))
        return ZeroOneAdamState(
            count=jnp.zeros((), jnp.int32), mu=_tree_zeros_like(params),
            nu=_tree_zeros_like(params), accum=zeros,
            lrs=jnp.zeros((), jnp.float32),
            var_interval=jnp.ones((), jnp.int32),
            var_counter=jnp.zeros((), jnp.int32),
            local_interval=jnp.ones((), jnp.int32),
            local_counter=jnp.zeros((), jnp.int32),
            worker_error=w_err, server_error=s_err)

    def update(grads, state, params, lr):
        count = state.count + 1
        lr = jnp.asarray(lr, jnp.float32)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        frozen = count > var_freeze_step

        # ---- phase 1: adaptive variance -------------------------------
        def warmup(op):
            g, st = op
            var_step = (count % st.var_interval) == 0

            def refresh(op2):
                g, st = op2
                if axis_name is not None:
                    g = jax.tree.map(lambda x: jax.lax.pmean(x, axis_name),
                                     g)
                mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x,
                                  st.mu, g)
                nu = jax.tree.map(lambda v, x: b2 * v + (1 - b2) * x * x,
                                  st.nu, g)
                # exponential interval growth (kappa refreshes per level)
                vc = st.var_counter + 1
                grow = vc >= var_update_scaler
                return (mu, nu, st.worker_error, st.server_error,
                        jnp.where(grow, 0, vc),
                        jnp.where(grow, st.var_interval * 2,
                                  st.var_interval))

            def between(op2):
                g, st = op2
                if axis_name is not None:
                    g, w_err, s_err = compressed_allreduce_tree(
                        g, st.worker_error, st.server_error, axis_name)
                else:
                    w_err, s_err = st.worker_error, st.server_error
                mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x,
                                  st.mu, g)
                return (mu, st.nu, w_err, s_err, st.var_counter,
                        st.var_interval)

            mu, nu, w_err, s_err, vc, vi = jax.lax.cond(
                var_step, refresh, between, (g, st))

            def upd(m, v, p):
                u = m / (jnp.sqrt(v) + eps)
                if weight_decay > 0.0:
                    u = u + weight_decay * p.astype(jnp.float32)
                return (-lr * u).astype(p.dtype)
            deltas = jax.tree.map(upd, mu, nu, params)
            return (deltas, mu, nu, st.accum, jnp.float32(0.0), vi, vc,
                    st.local_interval, st.local_counter, w_err, s_err)

        # ---- phase 2: frozen variance, local steps --------------------
        def local_phase(op):
            g, st = op
            # re-zero the error feedback at the phase boundary (reference
            # reinitial_error_buffer): phase-1 errors are gradient-scale,
            # phase-2 compresses the ~lr-times-smaller update accumulator —
            # stale errors would swamp it
            first_local = count == (var_freeze_step + 1)
            st = st.replace(
                worker_error=jax.tree.map(
                    lambda e: jnp.where(first_local, 0.0, e),
                    st.worker_error),
                server_error=jax.tree.map(
                    lambda e: jnp.where(first_local, 0.0, e),
                    st.server_error))
            mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x, st.mu, g)
            lrs = st.lrs + lr

            def upd(m, v, p):
                u = m / (jnp.sqrt(v) + eps)
                if weight_decay > 0.0:
                    u = u + weight_decay * p.astype(jnp.float32)
                return -lr * u
            delta_local = jax.tree.map(upd, mu, st.nu, params)
            accum = jax.tree.map(jnp.add, st.accum, delta_local)
            sync = (count % st.local_interval) == 0

            def do_sync(op2):
                mu, accum, st, delta_local = op2
                # roll the whole window back, exchange the accumulated
                # update in momentum units, re-apply the synced average
                in_momentum_units = jax.tree.map(
                    lambda a, v: a * (jnp.sqrt(v) + eps), accum, st.nu)
                if axis_name is not None:
                    synced, w_err, s_err = compressed_allreduce_tree(
                        in_momentum_units, st.worker_error,
                        st.server_error, axis_name)
                else:
                    synced = in_momentum_units
                    w_err, s_err = st.worker_error, st.server_error
                applied = jax.tree.map(
                    lambda s_, v: s_ / (jnp.sqrt(v) + eps), synced, st.nu)
                deltas = jax.tree.map(
                    lambda d, a, ap: (d - a + ap),
                    delta_local, accum, applied)
                # lrs == 0 (schedule decayed to zero across the window)
                # means nothing was applied and synced == 0: re-seed the
                # momentum to 0 rather than 0/0 = NaN
                safe_lrs = jnp.where(lrs > 0, lrs, 1.0)
                new_mu = jax.tree.map(lambda s_: -s_ / safe_lrs, synced)
                lc = st.local_counter + 1
                grow = lc >= local_step_scaler
                li = jnp.where(
                    grow, jnp.minimum(st.local_interval * 2,
                                      local_step_clipper),
                    st.local_interval)
                return (deltas, new_mu,
                        jax.tree.map(jnp.zeros_like, accum),
                        jnp.float32(0.0), li, jnp.where(grow, 0, lc),
                        w_err, s_err)

            def no_sync(op2):
                mu, accum, st, delta_local = op2
                return (delta_local, mu, accum, lrs, st.local_interval,
                        st.local_counter, st.worker_error, st.server_error)

            deltas, mu, accum, lrs, li, lc, w_err, s_err = jax.lax.cond(
                sync, do_sync, no_sync, (mu, accum, st, delta_local))
            deltas = jax.tree.map(lambda d, p: d.astype(p.dtype), deltas,
                                  params)
            return (deltas, mu, st.nu, accum, lrs, st.var_interval,
                    st.var_counter, li, lc, w_err, s_err)

        (deltas, mu, nu, accum, lrs, vi, vc, li, lc, w_err, s_err) = \
            jax.lax.cond(frozen, local_phase, warmup, (grads, state))
        return deltas, ZeroOneAdamState(
            count=count, mu=mu, nu=nu, accum=accum, lrs=lrs,
            var_interval=vi, var_counter=vc, local_interval=li,
            local_counter=lc, worker_error=w_err, server_error=s_err)

    return Optimizer(init=init, update=update)


@struct.dataclass
class OnebitLambState:
    count: jnp.ndarray
    mu: any
    nu: any                 # frozen-at-warmup-end second moment
    nu_fresh: any           # kept fresh from reconstructed gradients
    coeff_freeze: any       # per-tensor EMA of the warmup trust ratio
    last_factor: any        # per-tensor rate-limited variance factor
    scaling_coeff: any      # per-tensor momentum pre-scaling for compression
    worker_error: any
    server_error: any


def onebit_lamb(betas=(0.9, 0.999), eps: float = 1e-8,
                weight_decay: float = 0.0, freeze_step: int = 100000,
                max_coeff: float = 10.0, min_coeff: float = 0.01,
                coeff_beta: float = 0.9, factor_max: float = 4.0,
                factor_min: float = 0.5, factor_threshold: float = 0.1,
                axis_name: Optional[str] = None,
                bias_correction: bool = True,
                amsgrad: bool = False, max_grad_norm: float = 0.0,
                eps_inside_sqrt: bool = False,
                cuda_aware: bool = False,
                comm_backend_name: str = "xla") -> Optimizer:
    """1-bit LAMB (reference runtime/fp16/onebit/lamb.py).

    Warmup (< ``freeze_step``): baseline LAMB — per-tensor trust ratio
    ``clamp(||p|| / ||m/(sqrt(v)+eps) + wd p||, min_coeff, max_coeff)``,
    while an EMA (``coeff_beta``) of the ratio is recorded per tensor.

    Compression stage: the second moment freezes; the momentum advances
    with the LOCAL gradient, is pre-scaled by ``scaling_coeff`` (computed
    once at the freeze boundary so all tensors compress at a comparable
    RMS), 1-bit-allreduced, and unscaled. The trust ratio is no longer
    recomputed from unstable compressed updates — instead the frozen EMA
    is modulated by ``factor = max(frozen_denom / fresh_denom)``, where
    the fresh variance tracks gradients reconstructed from consecutive
    momenta; the factor is clamped to [factor_min, factor_max] and rate-
    limited to ±factor_threshold per step. No bias correction regardless
    of ``bias_correction``, matching the reference update rule.
    """
    b1, b2 = betas
    _check_reference_extras(amsgrad, max_grad_norm, eps_inside_sqrt)

    def _tensor_scalar_tree(params, val):
        return jax.tree.map(lambda _: jnp.asarray(val, jnp.float32), params)

    def init(params):
        w_err, s_err = init_error_feedback(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))
        return OnebitLambState(
            count=jnp.zeros((), jnp.int32), mu=_tree_zeros_like(params),
            nu=_tree_zeros_like(params), nu_fresh=_tree_zeros_like(params),
            coeff_freeze=_tensor_scalar_tree(params, 0.0),
            last_factor=_tensor_scalar_tree(params, 1.0),
            scaling_coeff=_tensor_scalar_tree(params, 1.0),
            worker_error=w_err, server_error=s_err)

    def update(grads, state, params, lr):
        count = state.count + 1
        lr = jnp.asarray(lr, jnp.float32)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        frozen = count > freeze_step

        def warmup(op):
            g, st = op
            if axis_name is not None:
                g = jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), g)
            mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x, st.mu, g)
            nu = jax.tree.map(lambda v, x: b2 * v + (1 - b2) * x * x,
                              st.nu, g)

            def per_tensor(m, v, p, cf):
                upd = m / (jnp.sqrt(v) + eps)
                if weight_decay > 0.0:
                    upd = upd + weight_decay * p.astype(jnp.float32)
                wnorm = jnp.linalg.norm(p.astype(jnp.float32))
                unorm = jnp.linalg.norm(upd)
                raw = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm,
                                1.0)
                coeff = jnp.clip(raw, min_coeff, max_coeff)
                new_cf = jnp.where(
                    coeff != 1.0,
                    coeff_beta * cf + (1 - coeff_beta) * coeff, cf)
                return (-lr * coeff * upd).astype(p.dtype), new_cf
            out = jax.tree.map(per_tensor, mu, nu, params, st.coeff_freeze)
            deltas = jax.tree.map(lambda o: o[0], out,
                                  is_leaf=lambda x: isinstance(x, tuple))
            coeff_freeze = jax.tree.map(
                lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))

            # boundary bookkeeping, branchless: at count == freeze_step,
            # snapshot nu into nu_fresh and derive scaling coefficients
            at_freeze = count == freeze_step
            nu_fresh = jax.tree.map(
                lambda vf, v: jnp.where(at_freeze, v, vf), st.nu_fresh, nu)
            rms = jax.tree.map(
                lambda m: jnp.linalg.norm(m) / jnp.sqrt(jnp.float32(m.size)),
                mu)
            rms_leaves = jax.tree.leaves(rms)
            united = sum(rms_leaves) / len(rms_leaves)
            scaling = jax.tree.map(
                lambda sc, r: jnp.where(at_freeze,
                                        united / jnp.maximum(r, 1e-30), sc),
                st.scaling_coeff, rms)
            return (deltas, mu, nu, nu_fresh, coeff_freeze, st.last_factor,
                    scaling, st.worker_error, st.server_error)

        def compressed(op):
            g, st = op
            mu_last = st.mu
            mu = jax.tree.map(lambda m, x: b1 * m + (1 - b1) * x, st.mu, g)
            scaled = jax.tree.map(jnp.multiply, mu, st.scaling_coeff)
            if axis_name is not None:
                scaled, w_err, s_err = compressed_allreduce_tree(
                    scaled, st.worker_error, st.server_error, axis_name)
            else:
                w_err, s_err = st.worker_error, st.server_error
            mu = jax.tree.map(jnp.divide, scaled, st.scaling_coeff)
            g_rec = jax.tree.map(
                lambda m, ml: (m - ml * b1) / (1 - b1), mu, mu_last)
            nu_fresh = jax.tree.map(
                lambda vf, x: b2 * vf + (1 - b2) * x * x, st.nu_fresh,
                g_rec)

            def per_tensor(m, v, vf, p, cf, lf):
                denom = jnp.sqrt(v) + eps
                denom_real = jnp.sqrt(vf) + eps
                prelim = m / denom
                upd = prelim
                factor = jnp.max(denom / denom_real)
                if weight_decay > 0.0:
                    upd = prelim + weight_decay * p.astype(jnp.float32)
                    ratio = jnp.minimum(
                        1.0, jnp.linalg.norm(prelim) /
                        jnp.maximum(jnp.linalg.norm(upd), 1e-30))
                    factor = factor * ratio + (1.0 - ratio)
                factor = jnp.clip(factor, factor_min, factor_max)
                factor = jnp.clip(factor, lf * (1.0 - factor_threshold),
                                  lf * (1.0 + factor_threshold))
                coeff = cf * factor
                return (-lr * coeff * upd).astype(p.dtype), factor
            out = jax.tree.map(per_tensor, mu, st.nu, nu_fresh, params,
                               st.coeff_freeze, st.last_factor)
            deltas = jax.tree.map(lambda o: o[0], out,
                                  is_leaf=lambda x: isinstance(x, tuple))
            last_factor = jax.tree.map(
                lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
            return (deltas, mu, st.nu, nu_fresh, st.coeff_freeze,
                    last_factor, st.scaling_coeff, w_err, s_err)

        (deltas, mu, nu, nu_fresh, coeff_freeze, last_factor, scaling,
         w_err, s_err) = jax.lax.cond(frozen, compressed, warmup,
                                      (grads, state))
        return deltas, OnebitLambState(
            count=count, mu=mu, nu=nu, nu_fresh=nu_fresh,
            coeff_freeze=coeff_freeze, last_factor=last_factor,
            scaling_coeff=scaling, worker_error=w_err, server_error=s_err)

    return Optimizer(init=init, update=update)
