"""SwitchBack-style int8 training linear for the TPU MXU.

The v5e MXU multiplies int8×int8 at twice the bf16 rate (394 TOPS vs
197 TFLOPS), so running the training GEMMs on int8 operands raises the
compute ceiling — a TPU-native capability beyond the reference, whose
compression stack quantizes only for memory/serving (MoQ,
``deepspeed/compression/basic_layer.py``; our serving analog is
``ops/int8_gemm.py``). This op brings the same w8a8 arithmetic to the
TRAINING step with straight-through gradients (public technique:
"SwitchBack" — Wortsman et al., Stable and low-precision training for
large-scale vision-language models, 2023):

* forward:  ``y = (q(x) @ q(w)) * sx * sw`` — per-token activation
  scales, per-output-channel weight scales, int8 dot with an int32
  accumulator (exact), one fp rescale.
* ``dx = (q(dy) @ q(wᵀ)) * sdy * swt`` — the second-largest GEMM also
  rides the int8 MXU path (per-token dy scales; per-TENSOR weight scale
  for the transpose, whose per-column grid does not transpose).
* ``dw = xᵀ @ dy`` stays full precision (fp32 accumulation): weight
  gradients feed the optimizer and are the accuracy-critical third.

Two of the three step GEMMs run at the doubled int8 rate; master
weights, optimizer state, and everything outside the projections are
untouched, so the mode composes with ZeRO/offload/precision unchanged.
Opt-in via ``int8_training=True`` on the model config; fake-quant noise
acts like QAT (see tests/test_int8_training.py for the convergence
parity evidence).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quant_core import quantize_int8 as _quant

# _quant: symmetric int8 along an axis with the amax/127 scale — the
# shared definition lives in ops/quant_core.py (also the int8 paged KV
# cache's writer quantizer, inference/kv_cache.py). The serving-side
# weight path (ops/int8_gemm.py) stays separate on purpose — it
# quantizes against STORED {"q","oscale"} trees, not live bf16.


def _quant_lastdim(x: jax.Array):
    """Per row/token: q, scale [..., 1]."""
    return _quant(x, -1)


def _quant_cols(w: jax.Array):
    """Per output column of ``w [K, N]``: q, scale [1, N]."""
    return _quant(w, 0)


def _quant_tensor(w: jax.Array):
    """ONE scale (for the bwd transpose)."""
    return _quant(w, None)


def _int8_dot_last(x_q: jax.Array, w_q: jax.Array) -> jax.Array:
    """``[..., K]int8 @ [K, N]int8 -> [..., N]int32`` on the MXU."""
    return jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


@jax.custom_vjp
def switchback_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """``x [..., K] @ w [K, N]`` with int8 fwd/dx and fp32-accum dw."""
    qx, sx = _quant_lastdim(x)
    qw, sw = _quant_cols(w)
    y = _int8_dot_last(qx, qw).astype(jnp.float32) * sx * sw
    return y.astype(x.dtype)


def _switchback_fwd(x, w):
    return switchback_matmul(x, w), (x, w)


def _switchback_bwd(res, dy):
    x, w = res
    # dx = dy @ w.T on the int8 MXU (per-token dy scale, per-tensor w)
    qdy, sdy = _quant_lastdim(dy)
    qwt, swt = _quant_tensor(jnp.swapaxes(w.astype(jnp.float32), 0, 1))
    dx = _int8_dot_last(qdy, qwt).astype(jnp.float32) * sdy * swt
    # dw = x.T @ dy full precision: contract every leading dim
    K, N = w.shape
    x2 = x.reshape(-1, K).astype(jnp.float32)
    dy2 = dy.reshape(-1, N).astype(jnp.float32)
    dw = jax.lax.dot_general(x2, dy2, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return dx.astype(x.dtype), dw.astype(w.dtype)


switchback_matmul.defvjp(_switchback_fwd, _switchback_bwd)


# Per-expert SwitchBack: ``x [E, T, K] @ w [E, K, N] -> [E, T, N]`` —
# the stacked-expert twin of switchback_matmul (MoE FFNs run one batched
# matmul over the expert dim, moe/layer.py Experts). vmapping the 2-D op
# over the expert axis reproduces the exact per-expert scale semantics
# (x per (expert, token); w per (expert, out-column); bwd w per-expert-
# tensor; dw full precision) while keeping ONE quant/VJP implementation
# — custom_vjp composes with vmap.
switchback_batched_matmul = jax.vmap(switchback_matmul)


def switchback_logits(x: jax.Array, w_vc: jax.Array) -> jax.Array:
    """``x [..., C] @ w_vc [V, C] -> [..., V]``: the LM-head/vocab
    projection on the int8 MXU (the weight arrives in embedding layout;
    the transpose is layout-assigned away by XLA). At small-model
    geometry the vocab GEMM is ~15-25% of the step FLOPs — the last
    large bf16 island once the block projections run int8."""
    return switchback_matmul(x, jnp.swapaxes(w_vc, 0, 1))


def lm_logits(x: jax.Array, w_vc: jax.Array, int8: bool) -> jax.Array:
    """THE vocab-projection seam for the model families: SwitchBack when
    int8 training is on, plain einsum otherwise — one place to change
    the head's quantization policy for gpt2/llama/bert alike."""
    if int8:
        return switchback_logits(x, w_vc)
    return jnp.einsum("...c,vc->...v", x, w_vc)


def maybe_switchback(enabled: bool):
    """``flax.linen.Dense(dot_general=...)`` value for a model config:
    the SwitchBack seam when int8 training is enabled, ``None`` (flax's
    stock ``lax.dot_general``) otherwise."""
    return switchback_dot_general if enabled else None


def switchback_dot_general(lhs, rhs, dimension_numbers, precision=None,
                           preferred_element_type=None):
    """``flax.linen.Dense(dot_general=...)`` seam: route the Dense
    pattern (last-dim × dim-0 contraction, no batch dims) through the
    int8 training matmul; anything else falls back to the stock dot."""
    expected = (((lhs.ndim - 1,), (0,)), ((), ()))
    if dimension_numbers == expected and rhs.ndim == 2:
        return switchback_matmul(lhs, rhs)
    return jax.lax.dot_general(lhs, rhs, dimension_numbers, precision,
                               preferred_element_type)
