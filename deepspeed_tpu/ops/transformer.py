"""Fused BERT-style TRAINING transformer layer.

Analog of the reference's flagship training kernel
(``ops/transformer/transformer.py:459`` ``DeepSpeedTransformerLayer`` +
``DeepSpeedTransformerConfig`` :38, backed by ~6k LoC of CUDA in
``csrc/transformer/`` — the "64 TFLOPS BERT layer"). On TPU the fusion the
CUDA code does by hand (bias+gelu into the FFN GEMM, bias+dropout+residual
into the projection, fp32 LayerNorm accumulation) is XLA's job; what
remains worth owning is the layer *contract*: the exact parameter set,
pre/post-LN orderings, dropout placement, and a Pallas flash-attention
core for the unmasked case.

Differences by design:
* ``stochastic_mode`` is accepted and ignored: it trades determinism for
  ~2% speed in the CUDA kernels; XLA programs are deterministic and the
  trade does not exist.
* weights use TPU-friendly ``[in, out]`` layout (the reference stores
  torch's ``[out, in]``); ``from_torch_layout`` converts.

Parameter schema (names mirror the reference's attributes)::

    attn_qkvw [E, 3E]  attn_qkvb [3E]
    attn_ow   [E, E]   attn_ob   [E]
    attn_nw/attn_nb    [E]           attention LayerNorm
    inter_w   [E, F]   inter_b   [F]
    output_w  [F, E]   output_b  [E]
    norm_w/norm_b      [E]           FFN LayerNorm
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


def layer_norm_fp32(x, scale, bias, eps):
    """fp32-accumulation LayerNorm (the reference's normalize_kernels.cu
    semantics) — THE shared implementation for the training stack."""
    m = jnp.mean(x.astype(jnp.float32), -1, keepdims=True)
    v = jnp.var(x.astype(jnp.float32), -1, keepdims=True)
    y = (x.astype(jnp.float32) - m) * jax.lax.rsqrt(v + eps)
    return (y * scale.astype(jnp.float32) +
            bias.astype(jnp.float32)).astype(x.dtype)


@dataclasses.dataclass(frozen=True)
class DeepSpeedTransformerConfig:
    """Reference config surface (transformer.py:38) minus CUDA-isms."""
    batch_size: int = -1                  # API parity; shapes come from x
    hidden_size: int = 768
    intermediate_size: Optional[int] = None
    heads: int = 12
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1                  # API parity
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False    # memory trick subsumed by remat
    gelu_checkpoint: bool = False         # ditto
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False         # no-op: XLA is deterministic
    return_tuple: bool = False
    training: bool = True
    # SwitchBack int8 projections (ops/int8_training.py): qkv/attn-out/
    # FFN GEMMs run int8 x int8 on the MXU; dw stays full precision
    int8_training: bool = False

    @property
    def ffn(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def dtype(self):
        return jnp.bfloat16 if self.fp16 else jnp.float32


class DeepSpeedTransformerLayer:
    """Functional encoder layer: ``init(rng) -> params``;
    ``apply(params, x, attention_mask=None, rng=None) -> y``."""

    layer_id = 0

    def __init__(self, config: DeepSpeedTransformerConfig):
        self.config = config
        self.layer_id = DeepSpeedTransformerLayer.layer_id
        DeepSpeedTransformerLayer.layer_id += 1

    # -- params -----------------------------------------------------------
    def init(self, rng) -> Dict[str, Any]:
        cfg = self.config
        E, F = cfg.hidden_size, cfg.ffn
        std = cfg.initializer_range
        if cfg.adjust_init_range and cfg.num_hidden_layers > 0:
            # output-projection init shrinks with depth (reference
            # init_transformer_weights output_std = std / sqrt(2L))
            out_std = std / math.sqrt(2.0 * cfg.num_hidden_layers)
        else:
            out_std = std
        k = iter(jax.random.split(rng, 4))

        def normal(key, shape, s):
            return (jax.random.normal(key, shape, jnp.float32) * s
                    ).astype(cfg.dtype)
        return {
            "attn_qkvw": normal(next(k), (E, 3 * E), std),
            "attn_qkvb": jnp.zeros((3 * E,), cfg.dtype),
            "attn_ow": normal(next(k), (E, E), out_std),
            "attn_ob": jnp.zeros((E,), cfg.dtype),
            "attn_nw": jnp.ones((E,), cfg.dtype),
            "attn_nb": jnp.zeros((E,), cfg.dtype),
            "inter_w": normal(next(k), (E, F), std),
            "inter_b": jnp.zeros((F,), cfg.dtype),
            "output_w": normal(next(k), (F, E), out_std),
            "output_b": jnp.zeros((E,), cfg.dtype),
            "norm_w": jnp.ones((E,), cfg.dtype),
            "norm_b": jnp.zeros((E,), cfg.dtype),
        }

    @staticmethod
    def from_torch_layout(qkvw, qkvb, ow, ob, attn_nw, attn_nb, inter_w,
                          inter_b, output_w, output_b, norm_w, norm_b,
                          dtype=jnp.float32) -> Dict[str, Any]:
        """Reference/torch ``[out, in]`` tensors → this layer's params."""
        import numpy as np
        t = lambda a: jnp.asarray(np.asarray(a), dtype)  # noqa: E731
        return {"attn_qkvw": t(qkvw).T, "attn_qkvb": t(qkvb),
                "attn_ow": t(ow).T, "attn_ob": t(ob),
                "attn_nw": t(attn_nw), "attn_nb": t(attn_nb),
                "inter_w": t(inter_w).T, "inter_b": t(inter_b),
                "output_w": t(output_w).T, "output_b": t(output_b),
                "norm_w": t(norm_w), "norm_b": t(norm_b)}

    # -- forward ----------------------------------------------------------
    def _ln(self, x, w, b):
        return layer_norm_fp32(x, w, b, self.config.layer_norm_eps)

    def _mm(self, x, w):
        """Projection GEMM seam: SwitchBack int8 dot when the config
        opts in (ops/int8_training.py), plain bf16 matmul otherwise."""
        if self.config.int8_training:
            from deepspeed_tpu.ops.int8_training import switchback_matmul
            return switchback_matmul(x, w)
        return x @ w

    def _dropout(self, x, rate, rng, deterministic):
        if deterministic or rate <= 0.0 or rng is None:
            return x, rng
        rng, sub = jax.random.split(rng)
        keep = jax.random.bernoulli(sub, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), 0).astype(x.dtype), rng

    def _attention(self, x, params, attention_mask, rng, deterministic):
        cfg = self.config
        B, T, E = x.shape
        H, D = cfg.heads, E // cfg.heads
        qkv = self._mm(x, params["attn_qkvw"]) + params["attn_qkvb"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        need_mask = attention_mask is not None
        drop_attn = (not deterministic and cfg.attn_dropout_ratio > 0.0
                     and rng is not None)
        # the Pallas kernel tiles at 128: lengths above one block must be
        # multiples of it (callers pad); otherwise use the einsum path
        flash_ok = T <= 128 or T % 128 == 0
        if not need_mask and not drop_attn and flash_ok:
            # Pallas flash core (bidirectional)
            from deepspeed_tpu.ops.pallas.flash_attention import (
                flash_attention)
            y = flash_attention(q, k, v, causal=False)
        else:
            scale = 1.0 / math.sqrt(D)
            att = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            if need_mask:
                m = attention_mask
                if m.ndim == 2:          # [B, T] HF key mask
                    m = m[:, None, None, :]
                att = jnp.where(m > 0, att, jnp.float32(-1e30))
            att = jax.nn.softmax(att.astype(jnp.float32), -1).astype(x.dtype)
            if drop_attn:
                att, rng = self._dropout(att, cfg.attn_dropout_ratio, rng,
                                         deterministic)
            y = jnp.einsum("bhqk,bkhd->bqhd", att, v)
        y = self._mm(y.reshape(B, T, E), params["attn_ow"]) \
            + params["attn_ob"]
        return y, rng

    def apply(self, params: Dict[str, Any], x,
              attention_mask=None, rng=None,
              deterministic: Optional[bool] = None):
        """x [B, T, E] → [B, T, E]; BERT orderings per pre_layer_norm
        (reference DeepSpeedTransformerFunction :152)."""
        cfg = self.config
        det = (not cfg.training) if deterministic is None else deterministic
        x = x.astype(cfg.dtype)
        if cfg.pre_layer_norm:
            h = self._ln(x, params["attn_nw"], params["attn_nb"])
            attn, rng = self._attention(h, params, attention_mask, rng, det)
            attn, rng = self._dropout(attn, cfg.hidden_dropout_ratio, rng,
                                      det)
            x = x + attn
            h = self._ln(x, params["norm_w"], params["norm_b"])
            ffn = jax.nn.gelu(
                (self._mm(h, params["inter_w"]) + params["inter_b"]
                 ).astype(jnp.float32), approximate=False).astype(cfg.dtype)
            ffn = self._mm(ffn, params["output_w"]) + params["output_b"]
            ffn, rng = self._dropout(ffn, cfg.hidden_dropout_ratio, rng, det)
            out = x + ffn
        else:  # post-LN (original BERT)
            attn, rng = self._attention(x, params, attention_mask, rng, det)
            attn, rng = self._dropout(attn, cfg.hidden_dropout_ratio, rng,
                                      det)
            x = self._ln(x + attn, params["attn_nw"], params["attn_nb"])
            ffn = jax.nn.gelu(
                (self._mm(x, params["inter_w"]) + params["inter_b"]
                 ).astype(jnp.float32), approximate=False).astype(cfg.dtype)
            ffn = self._mm(ffn, params["output_w"]) + params["output_b"]
            ffn, rng = self._dropout(ffn, cfg.hidden_dropout_ratio, rng, det)
            out = self._ln(x + ffn, params["norm_w"], params["norm_b"])
        if cfg.return_tuple:
            return (out,)
        return out

    __call__ = apply
