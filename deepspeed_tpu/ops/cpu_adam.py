"""DeepSpeedCPUAdam / DeepSpeedCPUAdagrad — host-offload optimizers.

Analog of ``deepspeed/ops/adam/cpu_adam.py:13`` (+ ``adagrad/cpu_adagrad.py``):
the fp32 master weights and moments live in host RAM as numpy arrays; the
fused SIMD step (csrc/cpu_adam.cpp) updates them in place and emits the
bf16 copy-back buffer that is pushed to the TPU — the ``fp16_param_groups``
overlapped-copy path of the reference (``cpu_adam.py:117``).

Falls back to a pure-numpy step when no C++ toolchain exists (the analog of
``is_compatible()`` gating).
"""
from __future__ import annotations

import ctypes
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import CPUAdamBuilder
from deepspeed_tpu.utils.logging import logger


def _as_f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _as_u16p(a: Optional[np.ndarray]):
    if a is None:
        return ctypes.POINTER(ctypes.c_uint16)()
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


class DeepSpeedCPUAdam:
    """Per-leaf host Adam over a pytree of flat fp32 numpy arrays."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True, use_native=True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self._lib = None
        if use_native:
            builder = CPUAdamBuilder()
            if builder.is_compatible():
                try:
                    self._lib = builder.load()
                except RuntimeError as e:
                    logger.warning(f"cpu_adam native build failed ({e}); "
                                   "using numpy fallback")

    @property
    def native(self) -> bool:
        return self._lib is not None

    def init_state(self, master: Dict[str, np.ndarray]):
        return {k: {"m": np.zeros_like(v), "v": np.zeros_like(v)}
                for k, v in master.items()}

    def step(self, master: Dict[str, np.ndarray],
             grads: Dict[str, np.ndarray], state: Dict[str, Any],
             lr: Optional[float] = None,
             bf16_out: Optional[Dict[str, np.ndarray]] = None,
             step: Optional[int] = None) -> None:
        """In-place update of every leaf. ``bf16_out[k]`` (uint16 view)
        receives the bf16 copy in the same pass when provided. ``step``
        pins the bias-correction step for leaf-at-a-time callers (NVMe
        swap loop) — default auto-increments once per call."""
        if step is None:
            self.step_count += 1
        else:
            self.step_count = int(step)
        lr = self.lr if lr is None else float(lr)
        for k, w in master.items():
            g = grads[k]
            st = state[k]
            out = None if bf16_out is None else bf16_out.get(k)
            if self._lib is not None:
                assert w.dtype == np.float32 and w.flags["C_CONTIGUOUS"]
                self._lib.dstpu_adam_update(
                    _as_f32p(w), _as_f32p(g), _as_f32p(st["m"]),
                    _as_f32p(st["v"]), w.size, self.step_count, lr,
                    self.beta1, self.beta2, self.eps, self.weight_decay,
                    1 if self.adamw_mode else 0, _as_u16p(out))
            else:
                self._numpy_step(w, g, st, lr, out)

    def _numpy_step(self, w, g, st, lr, out):
        if not self.adamw_mode and self.weight_decay > 0:
            g = g + self.weight_decay * w
        st["m"][:] = self.beta1 * st["m"] + (1 - self.beta1) * g
        st["v"][:] = self.beta2 * st["v"] + (1 - self.beta2) * g * g
        bc1 = 1 - self.beta1 ** self.step_count
        bc2 = 1 - self.beta2 ** self.step_count
        denom = np.sqrt(st["v"]) / np.sqrt(bc2) + self.eps
        if self.adamw_mode and self.weight_decay > 0:
            w *= 1 - lr * self.weight_decay
        w -= (lr / bc1) * st["m"] / denom
        if out is not None:
            out[:] = _f32_to_bf16_np(w)


class DeepSpeedCPUAdagrad:
    """Host Adagrad (reference ops/adagrad/cpu_adagrad.py)."""

    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0,
                 use_native=True):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self._lib = None
        if use_native:
            builder = CPUAdamBuilder()
            if builder.is_compatible():
                try:
                    self._lib = builder.load()
                except RuntimeError:
                    pass

    def init_state(self, master):
        return {k: {"h": np.zeros_like(v)} for k, v in master.items()}

    def step(self, master, grads, state, lr=None, bf16_out=None):
        lr = self.lr if lr is None else float(lr)
        for k, w in master.items():
            g = grads[k]
            st = state[k]
            out = None if bf16_out is None else bf16_out.get(k)
            if self._lib is not None:
                self._lib.dstpu_adagrad_update(
                    _as_f32p(w), _as_f32p(g), _as_f32p(st["h"]), w.size,
                    lr, self.eps, self.weight_decay, _as_u16p(out))
            else:
                gg = g + self.weight_decay * w if self.weight_decay else g
                st["h"] += gg * gg
                w -= lr * gg / (np.sqrt(st["h"]) + self.eps)
                if out is not None:
                    out[:] = _f32_to_bf16_np(w)


def _f32_to_bf16_np(w: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even fp32→bf16 (uint16 payload); NaN stays NaN
    (RNE carry would overflow a NaN mantissa into the Inf pattern)."""
    x = w.view(np.uint32)
    lsb = (x >> 16) & 1
    rounded = ((x + 0x7FFF + lsb) >> 16).astype(np.uint16)
    nan = (x & 0x7FFFFFFF) > 0x7F800000
    return np.where(nan, ((x >> 16) | 0x0040).astype(np.uint16), rounded)
