"""Pallas fused LayerNorm (+ optional residual add) with custom VJP.

Analog of the reference's training-kernel LayerNorm family
(``csrc/transformer/normalize_kernels.cu`` — fused LN with fp32
accumulation, plus the residual-fused variants in
``csrc/transformer/inference/csrc/layer_norm.cu``). XLA already fuses LN
chains well; this kernel exists for (a) the residual+LN fusion the inference
engine calls per layer and (b) saving (mean, rstd) residuals so backward
recomputes nothing.

x: [..., N] normalized over the last dim; weight/bias fp32 [N].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_ROWS = 128


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ln_fwd_kernel(x_ref, w_ref, b_ref, o_ref, mean_ref, rstd_ref,
                   *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    o_ref[:] = (xhat * w_ref[:].astype(jnp.float32) +
                b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(x_ref, w_ref, mean_ref, rstd_ref, g_ref,
                   dx_ref, dw_ref, db_ref, *, rows_total: int):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd
    gw = g * w
    n = x.shape[-1]
    # dx = rstd * (gw - mean(gw) - xhat * mean(gw * xhat))
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (gw - m1 - xhat * m2)).astype(dx_ref.dtype)

    # dw/db accumulate across row blocks (sequential grid on TPU)
    @pl.when(i == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)
    dw_ref[:] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(g, axis=0, keepdims=True)


def _ln_fwd(x2, w, b, *, eps, block_rows, interpret):
    R, N = x2.shape
    kernel = functools.partial(_ln_fwd_kernel, eps=eps)
    o, mean, rstd = pl.pallas_call(
        kernel,
        grid=(R // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, N), lambda i: (i, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, N), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, N), x2.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w[None], b[None])
    return o, mean, rstd


def _ln_bwd(x2, w, mean, rstd, g2, *, block_rows, interpret):
    R, N = x2.shape
    kernel = functools.partial(_ln_bwd_kernel, rows_total=R)
    dx, dw, db = pl.pallas_call(
        kernel,
        grid=(R // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, N), lambda i: (i, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, N), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, N), lambda i: (i, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, N), x2.dtype),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w[None], mean, rstd, g2)
    return dx, dw[0], db[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, weight, bias, eps: float = 1e-5):
    """LayerNorm over the last dim, fp32 accumulation. x: [..., N]."""
    o, _ = _fused_ln_fwd(x, weight, bias, eps)
    return o


def _pick_block_rows(rows: int) -> int:
    br = DEFAULT_BLOCK_ROWS
    while rows % br:
        br //= 2
    return max(br, 1)


def _fused_ln_fwd(x, weight, bias, eps):
    shape = x.shape
    N = shape[-1]
    x2 = x.reshape(-1, N)
    br = _pick_block_rows(x2.shape[0])
    o, mean, rstd = _ln_fwd(x2, weight, bias, eps=eps, block_rows=br,
                            interpret=_should_interpret())
    return o.reshape(shape), (x2, weight, mean, rstd, shape)


def _fused_ln_fwd_vjp(x, weight, bias, eps):
    return _fused_ln_fwd(x, weight, bias, eps)


def _fused_ln_bwd_vjp(eps, res, g):
    x2, weight, mean, rstd, shape = res
    g2 = g.reshape(x2.shape)
    br = _pick_block_rows(x2.shape[0])
    dx, dw, db = _ln_bwd(x2, weight, mean, rstd, g2, block_rows=br,
                         interpret=_should_interpret())
    return (dx.reshape(shape), dw.astype(weight.dtype),
            db.astype(weight.dtype))


fused_layer_norm.defvjp(_fused_ln_fwd_vjp, _fused_ln_bwd_vjp)


def fused_residual_layer_norm(x, residual, weight, bias, eps: float = 1e-5):
    """(x + residual) then LayerNorm — the per-layer inference fusion
    (reference ds_layer_norm_residual, layer_norm.cu). Returns (normed, sum)
    so the caller can carry the pre-norm residual stream."""
    s = x + residual
    return fused_layer_norm(s, weight, bias, eps), s


def layer_norm_reference(x, weight, bias, eps: float = 1e-5):
    """Numerics oracle."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    xhat = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (xhat * weight.astype(jnp.float32) +
            bias.astype(jnp.float32)).astype(x.dtype)
