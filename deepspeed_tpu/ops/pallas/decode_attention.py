"""Pallas decode attention over a KV cache — the inference hot path.

TPU-native analog of the reference's fused ``softmax_context`` kernel
(``csrc/transformer/inference/csrc/pt_binding.cpp:1701-1740`` /
``softmax.cu``), which attends one new token against the accumulated KV
cache each generation step. The kernel streams K/V blocks for one
(batch, head) through VMEM with the online-softmax recurrence and masks
positions beyond the live cache length — no [S] probability vector ever
round-trips HBM, and padding positions cost no exp/normalize work beyond
the masked block.

Layout: q ``[B, H, D]`` (one query token per sequence), cache ``[B, H, S, D]``
with per-sequence ``lengths [B]`` (scalar-prefetched so the loop bound is
known before the body runs).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
DEFAULT_BLOCK_K = 256


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                   s_max: int, scale: float):
    b = pl.program_id(0)
    length = len_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [1, D] (block (1,1,1,D))

    m = jnp.full((1, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((1, 1), jnp.float32)
    acc = jnp.zeros((1, q.shape[-1]), jnp.float32)

    num_kb = pl.cdiv(length, block_k)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [1, BK]
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(col < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m, l, acc))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def decode_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     lengths: jax.Array,
                     block_k: int = DEFAULT_BLOCK_K,
                     scale: float | None = None,
                     interpret: bool | None = None) -> jax.Array:
    """One-token attention against the KV cache.

    q: ``[B, H, D]``; k_cache/v_cache: ``[B, H, S, D]``; lengths: ``[B]``
    int32 live lengths (query attends cache positions ``< lengths[b]``).
    Returns ``[B, H, D]``.
    """
    B, H, D = q.shape
    S = k_cache.shape[2]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_k = min(block_k, S)
    if S % block_k:
        raise ValueError(f"cache size {S} not divisible by block_k {block_k}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    q4 = q[:, :, None, :]  # [B, H, 1, D]
    kernel = functools.partial(_decode_kernel, block_k=block_k, s_max=S,
                               scale=float(scale))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, H),
        in_specs=[
            pl.BlockSpec((1, 1, 1, D), lambda b, h, lens: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, lens: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, lens: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, D), lambda b, h, lens: (b, h, 0, 0)),
    )
    o4 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q4, k_cache, v_cache)
    return o4[:, :, 0, :]


def decode_attention_reference(q, k_cache, v_cache, lengths):
    """Numerics oracle (pure jnp, XLA) — also the CPU fallback path."""
    B, H, D = q.shape
    S = k_cache.shape[2]
    s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) / (D ** 0.5)
    mask = jnp.arange(S)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bhsd->bhd", p,
                      v_cache.astype(jnp.float32)).astype(q.dtype)
