"""Pallas decode attention over a KV cache — the inference hot path.

TPU-native analog of the reference's fused ``softmax_context`` kernel
(``csrc/transformer/inference/csrc/pt_binding.cpp:1701-1740`` /
``softmax.cu``), which attends one new token against the accumulated KV
cache each generation step. The kernel streams K/V blocks for one
(batch, kv-head) through VMEM with the online-softmax recurrence and
masks positions beyond the live cache length — no [S] probability vector
ever round-trips HBM, and dead cache tail costs nothing (the loop bound
comes from the scalar-prefetched lengths).

Decode is KV-bandwidth-bound, so the kernel consumes the cache in its
STORAGE layout ``[B, S, KH, D]`` (kv_cache.py) directly — r3 transposed
to [B, KH, S, D] before every call, a full cache read+write per token
per layer that roughly doubled decode HBM traffic. Grouped-query
attention is native: the grid is (batch, kv-head) and each program
attends that head's whole query group ``[R, D]`` against one K/V stream,
so GQA's bandwidth saving survives into decode (r3 fell back to an XLA
path that materialized the cache repeated to H heads).

Layout: q ``[B, H, D]`` (one query token per sequence, H = KH·R),
cache ``[B, S, KH, D]``, ``lengths [B]``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
DEFAULT_BLOCK_K = 256

# int8 paged pools (kv_cache_dtype: "int8", docs/serving.md "KV
# quantization & host tiering"): the paged kernels take the pool in its
# quantized storage layout plus per-block-per-head scale tiles
# ``[NB, KH, BS]`` (one amax/127 scale per written (position, head) row,
# block_size on the LANE dim so the scale block ``(1, 1, BS)`` loads
# contiguous lanes). Dequantization happens on the tile already in VMEM
# (int8 load * f32 scale), so the HBM stream is the int8 bytes — the
# whole point: decode is KV-bandwidth-bound and the cache just halved.


def _deq_tile(x_ref, s_ref, quantized: bool):
    """One K/V tile ``[BS, D]`` in f32 — int8 tiles multiply by their
    ``[BS]`` scale column in VMEM; fp tiles just upcast."""
    x = x_ref[0, :, 0, :].astype(jnp.float32)
    if quantized:
        x = x * s_ref[0, 0, :][:, None]
    return x


def _dequant_pools(k_pool, v_pool, k_scale, v_scale):
    """XLA-side pool dequantization for the reference oracles: scales
    ``[NB, KH, BS]`` broadcast against the ``[NB, BS, KH, D]`` pool."""
    from deepspeed_tpu.ops.quant_core import dequantize_int8
    if k_scale is None:
        return k_pool, v_pool
    k = dequantize_int8(k_pool,
                        jnp.transpose(k_scale, (0, 2, 1))[..., None])
    v = dequantize_int8(v_pool,
                        jnp.transpose(v_scale, (0, 2, 1))[..., None])
    return k, v


def _scale_specs(quantized: bool, BS: int, index_map):
    """The two extra in_specs an int8 pool adds (k_scale, v_scale) —
    empty for fp, so the fp kernel signature is byte-identical to the
    pre-quantization one."""
    if not quantized:
        return []
    spec = pl.BlockSpec((1, 1, BS), index_map)
    return [spec, spec]


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                   scale: float):
    b = pl.program_id(0)
    length = len_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [R, D]
    R = q.shape[0]

    m = jnp.full((R, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((R, 1), jnp.float32)
    acc = jnp.zeros((R, q.shape[-1]), jnp.float32)

    num_kb = pl.cdiv(length, block_k)

    def body(kb, carry):
        m, l, acc = carry
        # cache-native block [BK, D] (dim 2 of the [1, S, 1, D] ref is
        # the kv-head singleton selected by the index map)
        k = k_ref[0, pl.ds(kb * block_k, block_k), 0, :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [R,BK]
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (R, block_k), 1)
        s = jnp.where(col < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m, l, acc))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def decode_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     lengths: jax.Array,
                     block_k: int = DEFAULT_BLOCK_K,
                     scale: float | None = None,
                     interpret: bool | None = None) -> jax.Array:
    """One-token attention against the cache, GQA-native.

    q: ``[B, H, D]``; k_cache/v_cache: ``[B, S, KH, D]`` (the kv_cache.py
    storage layout — no transpose) with ``H % KH == 0``; lengths: ``[B]``
    int32 live lengths (query attends positions ``< lengths[b]``).
    Returns ``[B, H, D]``.
    """
    B, H, D = q.shape
    S, KH = k_cache.shape[1], k_cache.shape[2]
    if H % KH:
        raise ValueError(f"q heads {H} not divisible by kv heads {KH}")
    R = H // KH
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_k = min(block_k, S)
    if S % block_k:
        raise ValueError(f"cache size {S} not divisible by block_k {block_k}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [B, H, D] -> [B, KH, R, D]: group queries by the kv head they read
    qg = q.reshape(B, KH, R, D)
    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               scale=float(scale))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, KH),
        in_specs=[
            pl.BlockSpec((1, 1, R, D), lambda b, h, lens: (b, h, 0, 0)),
            pl.BlockSpec((1, S, 1, D), lambda b, h, lens: (b, 0, h, 0)),
            pl.BlockSpec((1, S, 1, D), lambda b, h, lens: (b, 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, R, D),
                               lambda b, h, lens: (b, h, 0, 0)),
    )
    og = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KH, R, D), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qg, k_cache, v_cache)
    return og.reshape(B, H, D)


def _paged_decode_kernel(len_ref, bt_ref, q_ref, k_ref, v_ref, *rest,
                         block_size: int, scale: float, quantized: bool):
    """Grid (slot, kv-head, block-table entry). The index maps gather K/V
    blocks straight out of the global pool through the scalar-prefetched
    block table — the kernel body only ever sees one ``[BS, D]`` block at
    logical position ``i*BS``, so no per-slot contiguous cache is ever
    materialized in HBM. Online-softmax state carries across the block
    dimension in VMEM scratch (the block axis is innermost, so one
    (slot, head) program's blocks run back-to-back on the core). An int8
    pool streams two extra ``[1, 1, BS]`` scale tiles per block and
    dequantizes in VMEM (:func:`_deq_tile`)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    s, i = pl.program_id(0), pl.program_id(2)
    length = len_ref[s]
    nb = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(i * block_size < length)
    def _update():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [R, D]
        R = q.shape[0]
        k = _deq_tile(k_ref, ks_ref, quantized)          # [BS, D]
        v = _deq_tile(v_ref, vs_ref, quantized)
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        col = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (R, block_size), 1)
        sc = jnp.where(col < length, sc, NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nb - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, block_tables: jax.Array,
                           lengths: jax.Array,
                           scale: float | None = None,
                           interpret: bool | None = None,
                           k_scale: jax.Array | None = None,
                           v_scale: jax.Array | None = None) -> jax.Array:
    """One-token attention through a paged KV pool, GQA-native.

    q: ``[S, H, D]`` (one query per slot); k_pool/v_pool:
    ``[NB, BS, KH, D]`` (the PagedKVCache per-layer pool layout);
    block_tables: ``[S, MB]`` int32 (entry j covers logical positions
    ``j*BS..(j+1)*BS-1``; dead entries must be valid ids — the null
    block); lengths: ``[S]`` int32 live lengths. Returns ``[S, H, D]``.

    int8 pools pass ``k_scale``/``v_scale`` ``[NB, KH, BS]`` and the
    kernel dequantizes each tile in VMEM — the grid, scratch, and
    online-softmax recurrence are unchanged (scales are two more
    streamed inputs, not a new program structure).

    Entirely-dead blocks (``i*BS >= lengths[s]``) are skipped by a
    ``pl.when`` guard, so an idle slot costs no VPU/MXU work beyond its
    DMA stream.
    """
    S, H, D = q.shape
    NB, BS, KH = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
    MB = block_tables.shape[1]
    if H % KH:
        raise ValueError(f"q heads {H} not divisible by kv heads {KH}")
    quantized = k_scale is not None
    if (k_pool.dtype == jnp.int8) != quantized:
        raise ValueError("int8 pools require k_scale/v_scale (and fp "
                         "pools must not pass them)")
    R = H // KH
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qg = q.reshape(S, KH, R, D)
    kernel = functools.partial(_paged_decode_kernel, block_size=BS,
                               scale=float(scale), quantized=quantized)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, KH, MB),
        in_specs=[
            pl.BlockSpec((1, 1, R, D), lambda s, h, i, lens, bt:
                         (s, h, 0, 0)),
            pl.BlockSpec((1, BS, 1, D), lambda s, h, i, lens, bt:
                         (bt[s, i], 0, h, 0)),
            pl.BlockSpec((1, BS, 1, D), lambda s, h, i, lens, bt:
                         (bt[s, i], 0, h, 0)),
        ] + _scale_specs(quantized, BS, lambda s, h, i, lens, bt:
                         (bt[s, i], h, 0)),
        out_specs=pl.BlockSpec((1, 1, R, D), lambda s, h, i, lens, bt:
                               (s, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, D), jnp.float32),
        ],
    )
    args = [lengths.astype(jnp.int32), block_tables.astype(jnp.int32),
            qg, k_pool, v_pool]
    if quantized:
        args += [k_scale, v_scale]
    og = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, KH, R, D), q.dtype),
        interpret=interpret,
    )(*args)
    return og.reshape(S, H, D)


def _paged_chunk_kernel(start_ref, bt_ref, q_ref, k_ref, v_ref, *rest,
                        block_size: int, rep: int, scale: float,
                        quantized: bool):
    """Chunked-prefill attention for ONE slot: grid (kv-head,
    block-table entry). Queries are the in-flight C-token chunk at
    absolute positions ``start..start+C-1``; keys stream out of the
    paged pool through the scalar-prefetched block table, so the chunk
    attends over the already-resident prefix (earlier chunks AND
    prefix-cache hits) plus itself without ever materializing a
    contiguous per-slot cache. Per-query causal bound: key position
    ``col`` is visible to chunk query ``qi`` iff ``col <= start + qi``.
    Online-softmax carry in VMEM scratch across the (innermost) block
    axis — the same recurrence as :func:`_paged_decode_kernel`, with
    the query dim widened from one token's head group to C·R rows."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    i = pl.program_id(1)
    nb = pl.num_programs(1)
    start = start_ref[0]
    CR = q_ref.shape[1]

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # blocks wholly beyond the chunk's last query are dead for every row
    @pl.when(i * block_size <= start + CR // rep - 1)
    def _update():
        q = q_ref[0].astype(jnp.float32) * scale         # [CR, D]
        k = _deq_tile(k_ref, ks_ref, quantized)          # [BS, D]
        v = _deq_tile(v_ref, vs_ref, quantized)
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        col = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (CR, block_size), 1)
        qi = jax.lax.broadcasted_iota(jnp.int32, (CR, block_size), 0) // rep
        sc = jnp.where(col <= start + qi, sc, NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nb - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def paged_chunk_attention(q: jax.Array, k_pool: jax.Array,
                          v_pool: jax.Array, block_table: jax.Array,
                          start: jax.Array,
                          scale: float | None = None,
                          interpret: bool | None = None,
                          k_scale: jax.Array | None = None,
                          v_scale: jax.Array | None = None) -> jax.Array:
    """Chunked-prefill attention for one slot through the paged pool,
    GQA-native.

    q: ``[C, H, D]`` (the in-flight chunk, absolute positions
    ``start..start+C-1``; the chunk's own k/v must already be written
    into the pool); k_pool/v_pool: ``[NB, BS, KH, D]``; block_table:
    ``[MB]`` int32 (the prefilling slot's row; dead entries must be
    valid ids — the null block); start: scalar int32, block-aligned.
    int8 pools pass ``k_scale``/``v_scale`` ``[NB, KH, BS]`` (VMEM
    dequant, same grid). Returns ``[C, H, D]``.
    """
    C, H, D = q.shape
    BS, KH = k_pool.shape[1], k_pool.shape[2]
    MB = block_table.shape[0]
    if H % KH:
        raise ValueError(f"q heads {H} not divisible by kv heads {KH}")
    quantized = k_scale is not None
    if (k_pool.dtype == jnp.int8) != quantized:
        raise ValueError("int8 pools require k_scale/v_scale (and fp "
                         "pools must not pass them)")
    R = H // KH
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [C, H, D] -> [KH, C*R, D]: rows grouped by the kv head they read,
    # query index recoverable in-kernel as row // R
    qg = q.reshape(C, KH, R, D).transpose(1, 0, 2, 3).reshape(KH, C * R, D)
    kernel = functools.partial(_paged_chunk_kernel, block_size=BS,
                               rep=R, scale=float(scale),
                               quantized=quantized)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(KH, MB),
        in_specs=[
            pl.BlockSpec((1, C * R, D), lambda h, i, st, bt: (h, 0, 0)),
            pl.BlockSpec((1, BS, 1, D), lambda h, i, st, bt:
                         (bt[i], 0, h, 0)),
            pl.BlockSpec((1, BS, 1, D), lambda h, i, st, bt:
                         (bt[i], 0, h, 0)),
        ] + _scale_specs(quantized, BS, lambda h, i, st, bt:
                         (bt[i], h, 0)),
        out_specs=pl.BlockSpec((1, C * R, D), lambda h, i, st, bt:
                               (h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((C * R, 1), jnp.float32),
            pltpu.VMEM((C * R, 1), jnp.float32),
            pltpu.VMEM((C * R, D), jnp.float32),
        ],
    )
    args = [jnp.reshape(start, (1,)).astype(jnp.int32),
            block_table.astype(jnp.int32), qg, k_pool, v_pool]
    if quantized:
        args += [k_scale, v_scale]
    og = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((KH, C * R, D), q.dtype),
        interpret=interpret,
    )(*args)
    return og.reshape(KH, C, R, D).transpose(1, 0, 2, 3).reshape(C, H, D)


def _paged_verify_kernel(len_ref, bt_ref, q_ref, k_ref, v_ref, *rest,
                         block_size: int, rep: int, spec: int,
                         scale: float, quantized: bool):
    """Speculative-verify attention for ALL slots: grid (slot, kv-head,
    block-table entry). Queries are each slot's K-token candidate chunk
    at absolute positions ``lengths[s]..lengths[s]+K-1`` (the chunk's
    own k/v already written into the pool at those positions —
    kv_cache.paged_write_tokens); keys stream out of the pool through
    the scalar-prefetched block table, per-query causal bound
    ``col <= lengths[s] + qi``. The same online-softmax recurrence as
    :func:`_paged_chunk_kernel`, with the per-slot ``lengths`` playing
    the chunk kernel's ``start`` role — so varying acceptance lengths
    ride as data, never as a new signature."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    s, i = pl.program_id(0), pl.program_id(2)
    nb = pl.num_programs(2)
    length = len_ref[s]
    KR = q_ref.shape[2]

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # blocks wholly beyond the chunk's last query position are dead for
    # every row of this slot
    @pl.when(i * block_size <= length + spec - 1)
    def _update():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [K*R, D]
        k = _deq_tile(k_ref, ks_ref, quantized)          # [BS, D]
        v = _deq_tile(v_ref, vs_ref, quantized)
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        col = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (KR, block_size), 1)
        qi = jax.lax.broadcasted_iota(jnp.int32, (KR, block_size), 0) // rep
        sc = jnp.where(col <= length + qi, sc, NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nb - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def paged_verify_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, block_tables: jax.Array,
                           lengths: jax.Array,
                           scale: float | None = None,
                           interpret: bool | None = None,
                           k_scale: jax.Array | None = None,
                           v_scale: jax.Array | None = None) -> jax.Array:
    """Batched speculative-verify attention through a paged KV pool,
    GQA-native.

    q: ``[S, K, H, D]`` (each slot's K-token candidate chunk at
    absolute positions ``lengths[s]..lengths[s]+K-1``; the chunk's own
    k/v must already be written into the pool); k_pool/v_pool:
    ``[NB, BS, KH, D]``; block_tables: ``[S, MB]`` int32 (dead entries
    must be valid ids — the null block); lengths: ``[S]`` int32 live
    lengths per slot. Returns ``[S, K, H, D]``.

    ONE kernel signature per ``(K, num_slots, block geometry)`` —
    per-slot acceptance state rides in ``lengths``, so varying
    acceptance never retraces (the PR-8 trace-discipline contract).
    int8 pools pass ``k_scale``/``v_scale`` ``[NB, KH, BS]`` (VMEM
    dequant, same grid)."""
    S, K, H, D = q.shape
    BS, KH = k_pool.shape[1], k_pool.shape[2]
    MB = block_tables.shape[1]
    if H % KH:
        raise ValueError(f"q heads {H} not divisible by kv heads {KH}")
    quantized = k_scale is not None
    if (k_pool.dtype == jnp.int8) != quantized:
        raise ValueError("int8 pools require k_scale/v_scale (and fp "
                         "pools must not pass them)")
    R = H // KH
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [S, K, H, D] -> [S, KH, K*R, D]: rows grouped by the kv head they
    # read, query index recoverable in-kernel as row // R
    qg = q.reshape(S, K, KH, R, D).transpose(0, 2, 1, 3, 4).reshape(
        S, KH, K * R, D)
    kernel = functools.partial(_paged_verify_kernel, block_size=BS,
                               rep=R, spec=K, scale=float(scale),
                               quantized=quantized)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, KH, MB),
        in_specs=[
            pl.BlockSpec((1, 1, K * R, D), lambda s, h, i, lens, bt:
                         (s, h, 0, 0)),
            pl.BlockSpec((1, BS, 1, D), lambda s, h, i, lens, bt:
                         (bt[s, i], 0, h, 0)),
            pl.BlockSpec((1, BS, 1, D), lambda s, h, i, lens, bt:
                         (bt[s, i], 0, h, 0)),
        ] + _scale_specs(quantized, BS, lambda s, h, i, lens, bt:
                         (bt[s, i], h, 0)),
        out_specs=pl.BlockSpec((1, 1, K * R, D), lambda s, h, i, lens, bt:
                               (s, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((K * R, 1), jnp.float32),
            pltpu.VMEM((K * R, 1), jnp.float32),
            pltpu.VMEM((K * R, D), jnp.float32),
        ],
    )
    args = [lengths.astype(jnp.int32), block_tables.astype(jnp.int32),
            qg, k_pool, v_pool]
    if quantized:
        args += [k_scale, v_scale]
    og = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, KH, K * R, D), q.dtype),
        interpret=interpret,
    )(*args)
    return og.reshape(S, KH, K, R, D).transpose(0, 2, 1, 3, 4).reshape(
        S, K, H, D)


def paged_verify_attention_reference(q, k_pool, v_pool, block_tables,
                                     lengths, k_scale=None, v_scale=None):
    """Numerics oracle for :func:`paged_verify_attention`: gather each
    slot's cache through its table, dense masked softmax with the
    per-query causal bound ``col <= lengths[s] + qi``. int8 pools
    dequantize up front (:func:`_dequant_pools`)."""
    k_pool, v_pool = _dequant_pools(k_pool, v_pool, k_scale, v_scale)
    S, K, H, D = q.shape
    BS, KH = k_pool.shape[1], k_pool.shape[2]
    MB = block_tables.shape[1]
    rep = H // KH
    kc = k_pool[block_tables].reshape(S, MB * BS, KH, D)
    vc = v_pool[block_tables].reshape(S, MB * BS, KH, D)
    kc = jnp.repeat(kc, rep, axis=2) if rep > 1 else kc
    vc = jnp.repeat(vc, rep, axis=2) if rep > 1 else vc
    s = jnp.einsum("skhd,sphd->shkp", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) / (D ** 0.5)
    col = jnp.arange(MB * BS)[None, None, None, :]
    qi = jnp.arange(K)[None, None, :, None]
    s = jnp.where(col <= lengths[:, None, None, None] + qi, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("shkp,sphd->skhd", p,
                      vc.astype(jnp.float32)).astype(q.dtype)


def paged_chunk_attention_reference(q, k_pool, v_pool, block_table, start,
                                    k_scale=None, v_scale=None):
    """Numerics oracle for :func:`paged_chunk_attention`: gather the
    slot's cache through its table, dense masked softmax with the
    per-query causal bound ``col <= start + qi``. int8 pools
    dequantize up front."""
    k_pool, v_pool = _dequant_pools(k_pool, v_pool, k_scale, v_scale)
    C, H, D = q.shape
    BS, KH = k_pool.shape[1], k_pool.shape[2]
    MB = block_table.shape[0]
    rep = H // KH
    kc = k_pool[block_table].reshape(MB * BS, KH, D)
    vc = v_pool[block_table].reshape(MB * BS, KH, D)
    kc = jnp.repeat(kc, rep, axis=1) if rep > 1 else kc
    vc = jnp.repeat(vc, rep, axis=1) if rep > 1 else vc
    s = jnp.einsum("chd,shd->chs", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) / (D ** 0.5)
    col = jnp.arange(MB * BS)[None, None, :]
    qi = jnp.arange(C)[:, None, None]
    s = jnp.where(col <= start + qi, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("chs,shd->chd", p,
                      vc.astype(jnp.float32)).astype(q.dtype)


def paged_decode_attention_reference(q, k_pool, v_pool, block_tables,
                                     lengths, k_scale=None, v_scale=None):
    """Numerics oracle: gather each slot's cache through its block table
    (gathered position j IS logical position j), then run the dense
    masked-softmax reference. Same layouts as
    :func:`paged_decode_attention`; int8 pools dequantize up front."""
    k_pool, v_pool = _dequant_pools(k_pool, v_pool, k_scale, v_scale)
    S, MB = block_tables.shape
    BS = k_pool.shape[1]
    kc = k_pool[block_tables].reshape(S, MB * BS, *k_pool.shape[2:])
    vc = v_pool[block_tables].reshape(S, MB * BS, *v_pool.shape[2:])
    return decode_attention_reference(q, kc, vc, lengths)


def decode_attention_reference(q, k_cache, v_cache, lengths):
    """Numerics oracle (pure jnp, XLA) — also the CPU fallback path.
    Same layouts as :func:`decode_attention`."""
    B, H, D = q.shape
    S, KH = k_cache.shape[1], k_cache.shape[2]
    rep = H // KH
    kc = jnp.repeat(k_cache, rep, axis=2) if rep > 1 else k_cache
    vc = jnp.repeat(v_cache, rep, axis=2) if rep > 1 else v_cache
    s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) / (D ** 0.5)
    mask = jnp.arange(S)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", p,
                      vc.astype(jnp.float32)).astype(q.dtype)
