"""Pallas decode attention over a KV cache — the inference hot path.

TPU-native analog of the reference's fused ``softmax_context`` kernel
(``csrc/transformer/inference/csrc/pt_binding.cpp:1701-1740`` /
``softmax.cu``), which attends one new token against the accumulated KV
cache each generation step. The kernel streams K/V blocks for one
(batch, kv-head) through VMEM with the online-softmax recurrence and
masks positions beyond the live cache length — no [S] probability vector
ever round-trips HBM, and dead cache tail costs nothing (the loop bound
comes from the scalar-prefetched lengths).

Decode is KV-bandwidth-bound, so the kernel consumes the cache in its
STORAGE layout ``[B, S, KH, D]`` (kv_cache.py) directly — r3 transposed
to [B, KH, S, D] before every call, a full cache read+write per token
per layer that roughly doubled decode HBM traffic. Grouped-query
attention is native: the grid is (batch, kv-head) and each program
attends that head's whole query group ``[R, D]`` against one K/V stream,
so GQA's bandwidth saving survives into decode (r3 fell back to an XLA
path that materialized the cache repeated to H heads).

Layout: q ``[B, H, D]`` (one query token per sequence, H = KH·R),
cache ``[B, S, KH, D]``, ``lengths [B]``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
DEFAULT_BLOCK_K = 256


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                   scale: float):
    b = pl.program_id(0)
    length = len_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [R, D]
    R = q.shape[0]

    m = jnp.full((R, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((R, 1), jnp.float32)
    acc = jnp.zeros((R, q.shape[-1]), jnp.float32)

    num_kb = pl.cdiv(length, block_k)

    def body(kb, carry):
        m, l, acc = carry
        # cache-native block [BK, D] (dim 2 of the [1, S, 1, D] ref is
        # the kv-head singleton selected by the index map)
        k = k_ref[0, pl.ds(kb * block_k, block_k), 0, :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [R,BK]
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (R, block_k), 1)
        s = jnp.where(col < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m, l, acc))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def decode_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     lengths: jax.Array,
                     block_k: int = DEFAULT_BLOCK_K,
                     scale: float | None = None,
                     interpret: bool | None = None) -> jax.Array:
    """One-token attention against the cache, GQA-native.

    q: ``[B, H, D]``; k_cache/v_cache: ``[B, S, KH, D]`` (the kv_cache.py
    storage layout — no transpose) with ``H % KH == 0``; lengths: ``[B]``
    int32 live lengths (query attends positions ``< lengths[b]``).
    Returns ``[B, H, D]``.
    """
    B, H, D = q.shape
    S, KH = k_cache.shape[1], k_cache.shape[2]
    if H % KH:
        raise ValueError(f"q heads {H} not divisible by kv heads {KH}")
    R = H // KH
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_k = min(block_k, S)
    if S % block_k:
        raise ValueError(f"cache size {S} not divisible by block_k {block_k}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [B, H, D] -> [B, KH, R, D]: group queries by the kv head they read
    qg = q.reshape(B, KH, R, D)
    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               scale=float(scale))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, KH),
        in_specs=[
            pl.BlockSpec((1, 1, R, D), lambda b, h, lens: (b, h, 0, 0)),
            pl.BlockSpec((1, S, 1, D), lambda b, h, lens: (b, 0, h, 0)),
            pl.BlockSpec((1, S, 1, D), lambda b, h, lens: (b, 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, R, D),
                               lambda b, h, lens: (b, h, 0, 0)),
    )
    og = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KH, R, D), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qg, k_cache, v_cache)
    return og.reshape(B, H, D)


def decode_attention_reference(q, k_cache, v_cache, lengths):
    """Numerics oracle (pure jnp, XLA) — also the CPU fallback path.
    Same layouts as :func:`decode_attention`."""
    B, H, D = q.shape
    S, KH = k_cache.shape[1], k_cache.shape[2]
    rep = H // KH
    kc = jnp.repeat(k_cache, rep, axis=2) if rep > 1 else k_cache
    vc = jnp.repeat(v_cache, rep, axis=2) if rep > 1 else v_cache
    s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) / (D ** 0.5)
    mask = jnp.arange(S)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", p,
                      vc.astype(jnp.float32)).astype(q.dtype)
