"""Pallas flash attention (training) — fused causal attention for the MXU.

TPU-native replacement for the reference's fused attention-softmax kernels
(``csrc/transformer/softmax_kernels.cu:attn_softmax``, used by the training
transformer kernel N10). Instead of materializing the [T, T] attention matrix
in HBM, the kernel streams K/V blocks through VMEM with the online-softmax
recurrence, accumulating in fp32 — O(T) memory, MXU-shaped [128, D] matmuls.

Layout: q ``[B, T, H, D]``; k/v may carry fewer heads (``[B, T, HKV, D]``,
HKV | H — grouped-query attention without materializing repeated k/v).
The kernel works on ``[B*H, T, D]`` q with a (kv-head, group, q-block)
grid whose group axis revisits each K/V block, so one kv head streams
through VMEM once for its whole query group. K/V for one batch-head live
whole in VMEM (T·D·2B·2 ≤ ~8 MB ⇒ T ≤ 16k at D=128, independent of the
group size — longer sequences shard over the ``seq`` axis via ring
attention, see ops/ring_attention.py).

Backward is the standard two-kernel flash decomposition (dQ sweep over K
blocks; dK/dV sweep over Q blocks) wired through ``jax.custom_vjp`` with the
(out, logsumexp) residuals.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


def effective_block(block: int, seq: int) -> int:
    """Largest power-of-two fraction of the requested ``block`` >= 128
    that tiles ``seq`` exactly (callers gate on seq % 128 == 0, so 128
    always fits; the 256 default would otherwise reject seq = 384, 640,
    ...). A non-power-of-two request whose halvings never land on a
    divisor of a 128-multiple seq (e.g. 384 into seq 512) snaps to 128 —
    the MXU-minimum tile every such seq accepts — rather than returning
    a sub-128 block the kernel can neither run nor should ever label a
    record with. Ragged seqs (seq % 128 != 0) keep the non-dividing
    block so flash_attention still rejects them loudly, as before. Pure
    int math, shared with bench.py's record labeling so salvage/baseline
    keys always name the block that actually ran."""
    b = min(block, seq)
    while b > 128 and seq % b:
        b //= 2
    if seq % b and seq % 128 == 0:
        b = 128
    return b


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float,
                block_q: int, block_k: int, seq_len: int, causal: bool):
    qi = pl.program_id(2)
    # keep the dot INPUTS in the storage dtype (bf16): the MXU runs bf16
    # at full rate and accumulates fp32 via preferred_element_type; an
    # upfront fp32 cast would quarter the matmul throughput
    q = q_ref[0]  # [BQ, D]
    bq, d = q.shape
    # fold the softmax scale into q ONCE ([BQ, D] mul) instead of into
    # every [BQ, BK] score block: the kernel is VPU-bound at small D (the
    # dots are tiny, the elementwise passes over the score block are not),
    # so every saved pass over [BQ, BK] is wall-clock
    qs = (q.astype(jnp.float32) * scale).astype(q.dtype)

    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    if causal:
        # K blocks strictly below the diagonal are FULLY visible — only
        # the ≤ cdiv(bq, bk) diagonal blocks pay the iota/compare/select
        # masking passes (for kb < diag_start: (kb+1)·bk ≤ qi·bq, i.e.
        # every column precedes every row of this q block)
        diag_start = (qi * block_q) // block_k
        num_kb = diag_start + pl.cdiv(block_q, block_k)
    else:
        diag_start = num_kb = seq_len // block_k

    def make_body(masked):
        def body(kb, carry):
            m, l, acc = carry
            k = k_ref[0, pl.ds(kb * block_k, block_k), :]
            v = v_ref[0, pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if masked:
                row = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 0)
                col = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 1)
                s = jnp.where(row >= col, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p.astype(q.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new
        return body

    carry = jax.lax.fori_loop(0, diag_start, make_body(False), (m, l, acc))
    if causal:
        carry = jax.lax.fori_loop(diag_start, num_kb, make_body(True),
                                  carry)
    m, l, acc = carry
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)  # [BQ, 1]


def _flash_fwd(q3, k3, v3, *, scale, block_q, block_k, causal, interpret):
    """q3 ``[B*H, T, D]``; k3/v3 ``[B*HKV, T, D]`` (HKV | H — grouped-query
    attention streams each K/V head into VMEM ONCE for its whole query
    group: grid order is (kv-head, group, q-block) with the q-block axis
    fastest, so the K/V block index is constant across an entire group and
    pallas reloads it only when the kv-head changes)."""
    BH, T, D = q3.shape
    BKH = k3.shape[0]
    rep = BH // BKH
    grid = (BKH, rep, T // block_q)
    out_shape = [
        jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        # trailing singleton lane dim satisfies TPU tiling (block last dim
        # equals the array dim); keeps lse O(BH·T) instead of the official
        # kernel's 128-lane broadcast
        jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
    ]
    kernel = functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                               block_k=block_k, seq_len=T, causal=causal)
    qmap = lambda bkh, g, qi: (bkh * rep + g, qi, 0)  # noqa: E731
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), qmap),
            pl.BlockSpec((1, T, D), lambda bkh, g, qi: (bkh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bkh, g, qi: (bkh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), qmap),
            pl.BlockSpec((1, block_q, 1), qmap),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale: float, block_q: int, block_k: int,
                   seq_len: int, causal: bool):
    qi = pl.program_id(2)
    # bf16 dot inputs, fp32 accumulation (see _fwd_kernel note)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]  # [BQ, 1]
    delta = delta_ref[0]  # [BQ, 1]
    bq, d = q.shape
    dq = jnp.zeros((bq, d), jnp.float32)
    # scale folded into q for the score dot (see _fwd_kernel); the dq
    # accumulation uses raw k and applies scale once at the end, as before
    qs = (q.astype(jnp.float32) * scale).astype(q.dtype)

    if causal:
        diag_start = (qi * block_q) // block_k
        num_kb = diag_start + pl.cdiv(block_q, block_k)
    else:
        diag_start = num_kb = seq_len // block_k

    def make_body(masked):
        def body(kb, dq):
            k = k_ref[0, pl.ds(kb * block_k, block_k), :]
            v = v_ref[0, pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if masked:
                row = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 0)
                col = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 1)
                s = jnp.where(row >= col, s, NEG_INF)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta)).astype(q.dtype)
            return dq + jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return body

    dq = jax.lax.fori_loop(0, diag_start, make_body(False), dq)
    if causal:
        dq = jax.lax.fori_loop(diag_start, num_kb, make_body(True), dq)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                    block_q: int, block_k: int, seq_len: int, causal: bool,
                    rep: int):
    ki = pl.program_id(1)
    g = pl.program_id(2)
    # bf16 dot inputs, fp32 accumulation (see _fwd_kernel note)
    k = k_ref[0]  # [BK, D]
    v = v_ref[0]
    bk, d = k.shape

    # grouped-query attention: this K/V head serves `rep` query heads.
    # The group axis is the INNERMOST grid dim, so the dk/dv output block
    # is revisited on consecutive steps: fp32 VMEM scratch accumulates
    # across the group (q/do blocks stay (1, T, D) — no rep-times VMEM
    # inflation), and the final group member flushes to the output.
    @pl.when(g == 0)
    def _init():
        dk_acc[...] = jnp.zeros((bk, d), jnp.float32)
        dv_acc[...] = jnp.zeros((bk, d), jnp.float32)

    num_qb = seq_len // block_q
    if causal:
        # q blocks split three ways around this k block: before first_qb
        # nothing is visible (skipped), [first_qb, diag_end) touches the
        # diagonal (masked), [diag_end, num_qb) is fully visible — the
        # iota/compare/select passes run on ≤ cdiv(bk, bq) blocks only
        first_qb = (ki * block_k) // block_q
        diag_end = -(-((ki + 1) * block_k - 1) // block_q)  # ceil div
    else:
        first_qb = diag_end = 0
    # scale folded into the resident k for the score dot (see
    # _fwd_kernel); dk accumulates against raw q, scaled once at flush
    ks = (k.astype(jnp.float32) * scale).astype(k.dtype)

    def make_body(masked):
        def body(qb, carry):
            dk, dv = carry
            q = q_ref[0, pl.ds(qb * block_q, block_q), :]
            do = do_ref[0, pl.ds(qb * block_q, block_q), :]
            lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]  # [BQ, 1]
            delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
            s = jax.lax.dot_general(q, ks, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if masked:
                row = qb * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, bk), 0)
                col = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, bk), 1)
                s = jnp.where(row >= col, s, NEG_INF)
            p = jnp.exp(s - lse)
            p16 = p.astype(k.dtype)
            dv_new = dv + jax.lax.dot_general(
                p16, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta)).astype(k.dtype)
            dk_new = dk + jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk_new, dv_new
        return body

    carry = (dk_acc[...], dv_acc[...])
    if causal:
        carry = jax.lax.fori_loop(first_qb, diag_end, make_body(True),
                                  carry)
    dk, dv = jax.lax.fori_loop(diag_end, num_qb, make_body(False), carry)
    dk_acc[...] = dk
    dv_acc[...] = dv

    @pl.when(g == rep - 1)
    def _flush():
        dk_ref[0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q3, k3, v3, o3, lse, do3, *, scale, block_q, block_k,
               causal, interpret):
    BH, T, D = q3.shape
    BKH = k3.shape[0]
    rep = BH // BKH
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, T, 1]

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale,
                                  block_q=block_q, block_k=block_k,
                                  seq_len=T, causal=causal)
    qmap = lambda bkh, g, qi: (bkh * rep + g, qi, 0)  # noqa: E731
    kvmap = lambda bkh, g, qi: (bkh, 0, 0)  # noqa: E731
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BKH, rep, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), qmap),
            pl.BlockSpec((1, T, D), kvmap),
            pl.BlockSpec((1, T, D), kvmap),
            pl.BlockSpec((1, block_q, D), qmap),
            pl.BlockSpec((1, block_q, 1), qmap),
            pl.BlockSpec((1, block_q, 1), qmap),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), qmap),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale,
                                   block_q=block_q, block_k=block_k,
                                   seq_len=T, causal=causal, rep=rep)
    # group axis INNERMOST: consecutive grid steps revisit the same dk/dv
    # block (and the same k/v block), so the scratch accumulation in the
    # kernel is a legal sequential reduction and k/v stay resident in VMEM
    # across the whole query group
    gq = lambda bkh, ki, g: (bkh * rep + g, 0, 0)  # noqa: E731
    kvm = lambda bkh, ki, g: (bkh, ki, 0)  # noqa: E731
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BKH, T // block_k, rep),
        in_specs=[
            pl.BlockSpec((1, T, D), gq),
            pl.BlockSpec((1, block_k, D), kvm),
            pl.BlockSpec((1, block_k, D), kvm),
            pl.BlockSpec((1, T, D), gq),
            pl.BlockSpec((1, T, 1), gq),
            pl.BlockSpec((1, T, 1), gq),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), kvm),
            pl.BlockSpec((1, block_k, D), kvm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q3, k3, v3, scale, block_q, block_k, causal):
    o, _ = _flash_fwd(q3, k3, v3, scale=scale, block_q=block_q,
                      block_k=block_k, causal=causal,
                      interpret=_should_interpret())
    return o


def _flash_attention_fwd(q3, k3, v3, scale, block_q, block_k, causal):
    o, lse = _flash_fwd(q3, k3, v3, scale=scale, block_q=block_q,
                        block_k=block_k, causal=causal,
                        interpret=_should_interpret())
    return o, (q3, k3, v3, o, lse)


def _flash_attention_bwd(scale, block_q, block_k, causal, res, do3):
    q3, k3, v3, o3, lse = res
    dq, dk, dv = _flash_bwd(q3, k3, v3, o3, lse, do3, scale=scale,
                            block_q=block_q, block_k=block_k, causal=causal,
                            interpret=_should_interpret())
    return dq, dk, dv


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    scale: float | None = None):
    """Fused attention, ``q [B, T, H, D] -> [B, T, H, D]``.

    ``k``/``v`` may carry fewer heads (``[B, T, HKV, D]`` with HKV | H):
    grouped-query attention runs WITHOUT materializing the repeated k/v —
    each kv head streams through VMEM once for its whole query group, so
    GQA's HBM-bandwidth saving survives into the kernel (models pass
    unexpanded k/v; see models/llama.py).

    Sequence length must be divisible by the block sizes (the model layer
    pads to n_positions, itself a multiple of 128).
    """
    B, T, H, D = q.shape
    HKV = k.shape[2]
    if k.shape != v.shape or k.shape[:2] != (B, T) or k.shape[3] != D:
        raise ValueError(f"k/v shape {k.shape}/{v.shape} incompatible "
                         f"with q {q.shape}")
    if H % HKV:
        raise ValueError(f"q heads {H} not divisible by kv heads {HKV}")

    block_q = effective_block(block_q, T)
    block_k = effective_block(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(f"seq len {T} not divisible by blocks "
                         f"({block_q}, {block_k})")
    if scale is None:
        scale = 1.0 / math.sqrt(D)

    def to3(x):
        h = x.shape[2]
        return jnp.swapaxes(x, 1, 2).reshape(B * h, T, D)

    o3 = _flash_attention(to3(q), to3(k), to3(v), float(scale),
                          block_q, block_k, causal)
    return jnp.swapaxes(o3.reshape(B, H, T, D), 1, 2)
