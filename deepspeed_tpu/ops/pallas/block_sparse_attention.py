"""Pallas block-sparse flash attention.

The reference's sparse stack is three Triton kernels — SDD matmul, fused
block-sparse softmax, DSD matmul (``ops/sparse_attention/matmul.py:12``,
``softmax.py``) — plus a C++ LUT builder
(``csrc/sparse_attention/utils.cpp``). On TPU those fuse into ONE kernel:
for each (batch, head, q-block) the kernel walks only that row's active
key blocks (host-built LUT, scalar-prefetched) with the online-softmax
recurrence, so the sparse attention matrix never exists in HBM and skipped
blocks cost nothing.

Layout blocks must match the kernel block (≥128 recommended on TPU: MXU/
lane tiling; the reference defaults to 16 for Triton — configs port, just
pick a TPU-friendly ``block``).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def build_lut(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """layout [H, nb, nb] → (lut [H, nb, max_active] int32 padded with 0,
    counts [H, nb] int32). The utils.cpp analog, host-side."""
    H, nb, _ = layout.shape
    counts = layout.sum(-1).astype(np.int32)
    max_active = max(1, int(counts.max()))
    lut = np.zeros((H, nb, max_active), np.int32)
    for h in range(H):
        for qb in range(nb):
            cols = np.nonzero(layout[h, qb])[0]
            lut[h, qb, :len(cols)] = cols
    return lut, counts


def _kernel(counts_ref, lut_ref, q_ref, k_ref, v_ref, o_ref, *,
            block: int, scale: float, causal: bool):
    h = pl.program_id(1)
    qb = pl.program_id(2)
    count = counts_ref[h, qb]
    # bf16 dot inputs, fp32 accumulation via preferred_element_type —
    # an upfront fp32 cast would quarter the MXU rate (see
    # pallas/flash_attention.py)
    q = q_ref[0, 0]                                    # [block, D]
    D = q.shape[-1]

    m = jnp.full((block, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block, 1), jnp.float32)
    acc = jnp.zeros((block, D), jnp.float32)

    row = qb * block + jax.lax.broadcasted_iota(jnp.int32,
                                               (block, block), 0)

    def body(j, carry):
        m, l, acc = carry
        kb = lut_ref[h, qb, j]
        k = k_ref[0, 0, pl.ds(kb * block, block), :]
        v = v_ref[0, 0, pl.ds(kb * block, block), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            col = kb * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(col <= row, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(q.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, count, body, (m, l, acc))
    # rows whose every active block was causally masked (m never rose
    # above NEG_INF) must output zero, not mean(v): their p=exp(0)=1
    # weights are an artifact of the NEG_INF bookkeeping
    live = m > NEG_INF / 2
    out = jnp.where(live, acc / jnp.maximum(l, 1e-30), 0.0)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def block_sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           lut: jax.Array, counts: jax.Array,
                           block: int, causal: bool = False,
                           scale: float | None = None,
                           interpret: bool | None = None) -> jax.Array:
    """q/k/v ``[B, H, T, D]`` + LUT → ``[B, H, T, D]``. Rows whose count
    is 0 output zeros (fully-masked rows have no defined softmax — the
    reference's layouts never produce them)."""
    B, H, T, D = q.shape
    if T % block:
        raise ValueError(f"seq {T} not divisible by block {block}")
    nb = T // block
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nb),
        in_specs=[
            pl.BlockSpec((1, 1, block, D),
                         lambda b, h, qb, c, t: (b, h, qb, 0)),
            pl.BlockSpec((1, 1, T, D), lambda b, h, qb, c, t: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, T, D), lambda b, h, qb, c, t: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block, D),
                               lambda b, h, qb, c, t: (b, h, qb, 0)),
    )
    kernel = functools.partial(_kernel, block=block, scale=float(scale),
                               causal=causal)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=interpret,
    )(counts.astype(jnp.int32), lut.astype(jnp.int32), q, k, v)
