"""Random layerwise token dropping (Random-LTD) ops.

Analog of the reference CUDA kernels (``csrc/random_ltd/`` N7:
``token_sort_``, ``token_gather``, ``token_scatter_``,
``mask_gather_bert/gpt`` — ``pt_binding.cpp:210-214``) and their wrapper
(``deepspeed/ops/random_ltd/dropping_utils.py``). On TPU these are
gather/scatter shapes XLA compiles well — no custom kernel needed
(SURVEY §2.3 N7 port note).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def sample_token_indices(rng: jax.Array, seq_len: int, keep: int,
                         batch: int, sort: bool = True) -> jax.Array:
    """Sample ``keep`` token positions per sequence (reference
    ``token_sort_`` samples then sorts so relative order is preserved).
    Returns [batch, keep] int32."""
    def one(r):
        perm = jax.random.permutation(r, seq_len)[:keep]
        return jnp.sort(perm) if sort else perm
    return jax.vmap(one)(jax.random.split(rng, batch)).astype(jnp.int32)


def token_gather(x: jax.Array, indices: jax.Array) -> jax.Array:
    """Gather kept tokens: x [B, T, ...], indices [B, K] → [B, K, ...]
    (reference ``token_gather``)."""
    return jnp.take_along_axis(
        x, indices.reshape(indices.shape + (1,) * (x.ndim - 2)), axis=1)


def token_scatter(full: jax.Array, part: jax.Array,
                  indices: jax.Array) -> jax.Array:
    """Scatter processed tokens back into the full sequence: full [B, T, ...]
    (e.g. the layer input, for pass-through of dropped tokens), part
    [B, K, ...], indices [B, K] (reference ``token_scatter_``)."""
    def one(f, p, idx):
        return f.at[idx].set(p)
    return jax.vmap(one)(full, part, indices)


def gpt_attention_mask(indices: jax.Array, seq_len: int) -> jax.Array:
    """Causal mask restricted to kept tokens (reference ``mask_gather_gpt``):
    [B, K, K] bool where kept position i attends kept position j iff
    orig_pos[i] >= orig_pos[j]."""
    return indices[:, :, None] >= indices[:, None, :]


def bert_attention_mask(mask: jax.Array, indices: jax.Array) -> jax.Array:
    """Gather a [B, T] padding mask down to kept tokens [B, K]
    (reference ``mask_gather_bert``)."""
    return jnp.take_along_axis(mask, indices, axis=1)


def random_ltd_layer(layer_fn, x: jax.Array, rng: jax.Array,
                     keep: int) -> jax.Array:
    """Apply ``layer_fn`` to a random subset of tokens, passing the rest
    through unchanged (the reference's ``basic_layer.py:117`` wrapper).
    x: [B, T, C]."""
    B, T, _ = x.shape
    if keep >= T:
        return layer_fn(x)
    idx = sample_token_indices(rng, T, keep, B)
    part = token_gather(x, idx)
    out = layer_fn(part)
    return token_scatter(x, out, idx)
