"""DeepSpeed-Ulysses-style all-to-all sequence parallelism.

The second of the two first-class long-context modes (SURVEY §5.7 — the
reference snapshot has neither; ring attention lives in
``ops/ring_attention.py``). Ulysses (arXiv:2309.14509) keeps activations
sharded over the sequence axis everywhere EXCEPT inside attention: an
all-to-all re-partitions [B, T/sp, H, D] → [B, T, H/sp, D] (full sequence,
head subset), runs ordinary dense attention per local head group, and a
second all-to-all restores sequence sharding. Communication volume is
O(T·H·D/sp) per device — constant in sequence-parallel degree — versus the
ring's sp-1 neighbour hops; Ulysses wins when heads are plentiful and the
interconnect favours all-to-all (TPU ICI does), the ring wins when
sp > heads or memory must stay strictly O(T/sp) inside attention too.

Both entry points mirror ring_attention's: a shard_map-internal form and a
global-array wrapper. Requires ``n_head % sp == 0``.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.mesh import get_global_mesh

SEQ_AXIS = "seq"


def _dense_attention(q, k, v, causal, scale, block=0):
    """[B, T, h, D] full-sequence attention over the local head subset.

    The long-context point of Ulysses dies with an O(T²) score matrix, so
    the causal/default-scale case (what the gpt2 integration produces)
    routes through ``causal_attention`` — the Pallas flash kernel on TPU
    (``block`` = the flash tile override, cfg.flash_block). Other cases
    fall back to the shared dense oracle."""
    from deepspeed_tpu.ops.attention import (causal_attention,
                                             causal_attention_reference)
    default_scale = 1.0 / (q.shape[-1] ** 0.5)
    if causal and abs(scale - default_scale) < 1e-12:
        return causal_attention(q, k, v, block_q=block, block_k=block)
    return causal_attention_reference(q, k, v, scale=scale, causal=causal)


def ulysses_attention_sharded(q, k, v, axis_name: str = SEQ_AXIS,
                              causal: bool = True,
                              scale: Optional[float] = None,
                              block: int = 0):
    """Call INSIDE a shard_map manual over ``axis_name``.

    q/k/v: per-device sequence shards ``[B, T/sp, H, D]`` with
    ``H %%SP == 0``. Returns the same layout.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    sp = jax.lax.axis_size(axis_name)

    def seq_to_head(x):
        # [B, T/sp, H, D] → [B, T, H/sp, D]: scatter heads, gather seq
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def head_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    if q.shape[2] % sp:
        raise ValueError(f"n_head {q.shape[2]} not divisible by seq "
                         f"axis {sp} (use ring attention instead)")
    if k.shape[2] % sp:
        # grouped-query attention rides through natively when the kv
        # heads split evenly: rank r's H/sp query heads map exactly onto
        # its HKV/sp kv heads (H/sp is a multiple of the group size), so
        # the GQA-aware dense core computes the same result on
        # unexpanded k/v. An uneven split breaks that alignment.
        raise ValueError(
            f"n_kv_head {k.shape[2]} not divisible by seq axis {sp}: "
            f"expand k/v to the query head count first (jnp.repeat) or "
            f"use ring attention")
    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    out = _dense_attention(qh, kh, vh, causal, float(scale), block=block)
    return head_to_seq(out)


def ulysses_self_attention(q, k, v, mesh: Optional[Mesh] = None,
                           causal: bool = True,
                           scale: Optional[float] = None,
                           block: int = 0):
    """Global-array entry point: shards [B, T, H, D] over the ``seq`` axis
    and runs the all-to-all pair. Works inside jit (other mesh axes stay
    automatic)."""
    mesh = mesh or get_global_mesh()
    if SEQ_AXIS not in mesh.axis_names or mesh.shape[SEQ_AXIS] == 1:
        from deepspeed_tpu.ops.attention import causal_attention_reference
        return causal_attention_reference(q, k, v, scale=scale,
                                          causal=causal)
    sp = mesh.shape[SEQ_AXIS]
    if q.shape[1] % sp:
        raise ValueError(f"seq len {q.shape[1]} not divisible by seq "
                         f"axis {sp}")
    fn = functools.partial(ulysses_attention_sharded, causal=causal,
                           scale=scale, block=block)
    spec = P(None, SEQ_AXIS, None, None)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={SEQ_AXIS})(q, k, v)
