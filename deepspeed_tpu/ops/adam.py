"""Optimizers.

TPU-native equivalents of the reference's fused optimizer kernels:

* ``FusedAdam`` (csrc/adam/multi_tensor_adam.cu, ops/adam/fused_adam.py:16) —
  on TPU the entire update fuses under jit, so "fused Adam" is an
  optax-style AdamW whose update runs inside the compiled train step; the
  multi-tensor-apply machinery is unnecessary (XLA fuses across leaves).
* ``FusedLamb`` (csrc/lamb/fused_lamb_cuda.cu) — LAMB with trust-ratio
  clamping per the reference's ``max_coeff``/``min_coeff`` options.
* ``DeepSpeedCPUAdam`` (csrc/adam/cpu_adam.cpp) — host-offload variant; at
  this layer it is the same math, with placement handled by the engine's
  offload config (state on host memory). See runtime/offload.py.

All are expressed as (init_fn, update_fn) pairs on fp32 master params. The
update math matches torch AdamW (adamw_mode=True default in the reference,
fused_adam.py:16) so numerics line up with the reference's parity tests.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class AdamState:
    count: jnp.ndarray  # i32 step counter
    mu: any            # first moment
    nu: any            # second moment


class Optimizer(NamedTuple):
    init: callable   # params -> state
    update: callable  # (grads, state, params, lr) -> (updates, new_state)


def _tree_zeros_like(params):
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)


def adam(betas=(0.9, 0.999), eps: float = 1e-8, weight_decay: float = 0.0,
         adamw_mode: bool = True, bias_correction: bool = True, **_) -> Optimizer:
    """AdamW / Adam-with-L2 (reference default optimizer, FusedAdam)."""
    b1, b2 = betas

    def init(params):
        return AdamState(count=jnp.zeros((), jnp.int32),
                         mu=_tree_zeros_like(params),
                         nu=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf if bias_correction else jnp.float32(1.0)
        bc2 = 1.0 - b2 ** cf if bias_correction else jnp.float32(1.0)

        def leaf(g, m, v, p):
            g = g.astype(jnp.float32)
            if not adamw_mode and weight_decay > 0.0:
                g = g + weight_decay * p
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * (g * g)
            denom = jnp.sqrt(v_new / bc2) + eps
            upd = -(lr * (m_new / bc1) / denom)
            if adamw_mode and weight_decay > 0.0:
                upd = upd - lr * weight_decay * p
            return upd, m_new, v_new

        out = jax.tree.map(leaf, grads, state.mu, state.nu, params)
        updates = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        mu = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        nu = jax.tree.map(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return updates, AdamState(count=count, mu=mu, nu=nu)

    return Optimizer(init, update)


def lamb(betas=(0.9, 0.999), eps: float = 1e-6, weight_decay: float = 0.0,
         max_coeff: float = 10.0, min_coeff: float = 0.01,
         bias_correction: bool = True, **_) -> Optimizer:
    """LAMB (reference: FusedLamb, fused_lamb_cuda.cpp:108) — Adam direction
    scaled by the layerwise trust ratio ||p|| / ||update||, clamped to
    [min_coeff, max_coeff] as in the reference."""
    b1, b2 = betas

    def init(params):
        return AdamState(count=jnp.zeros((), jnp.int32),
                         mu=_tree_zeros_like(params),
                         nu=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf if bias_correction else jnp.float32(1.0)
        bc2 = 1.0 - b2 ** cf if bias_correction else jnp.float32(1.0)

        def leaf(g, m, v, p):
            g = g.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * (g * g)
            direction = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay > 0.0:
                direction = direction + weight_decay * p
            p_norm = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
            d_norm = jnp.linalg.norm(direction.reshape(-1))
            trust = jnp.where(
                (p_norm > 0.0) & (d_norm > 0.0),
                jnp.clip(p_norm / d_norm, min_coeff, max_coeff), 1.0)
            upd = -lr * trust * direction
            return upd, m_new, v_new

        out = jax.tree.map(leaf, grads, state.mu, state.nu, params)
        updates = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        mu = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        nu = jax.tree.map(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return updates, AdamState(count=count, mu=mu, nu=nu)

    return Optimizer(init, update)


def sgd(momentum: float = 0.0, weight_decay: float = 0.0, **_) -> Optimizer:
    @struct.dataclass
    class SGDState:
        count: jnp.ndarray
        mu: any

    def init(params):
        return SGDState(count=jnp.zeros((), jnp.int32),
                        mu=_tree_zeros_like(params) if momentum else None)

    def update(grads, state, params, lr):
        count = state.count + 1

        def leaf(g, m, p):
            g = g.astype(jnp.float32)
            if weight_decay > 0.0:
                g = g + weight_decay * p
            if momentum:
                m_new = momentum * m + g
                return -lr * m_new, m_new
            return -lr * g, None

        if momentum:
            out = jax.tree.map(leaf, grads, state.mu, params)
            updates = jax.tree.map(lambda o: o[0], out,
                                   is_leaf=lambda x: isinstance(x, tuple))
            mu = jax.tree.map(lambda o: o[1], out,
                              is_leaf=lambda x: isinstance(x, tuple))
            return updates, SGDState(count=count, mu=mu)
        updates = jax.tree.map(lambda g, p: leaf(g, None, p)[0], grads, params)
        return updates, SGDState(count=count, mu=None)

    return Optimizer(init, update)


def adagrad(eps: float = 1e-8, weight_decay: float = 0.0, **_) -> Optimizer:
    """Adagrad (reference: DeepSpeedCPUAdagrad, csrc/adagrad/cpu_adagrad.cpp)."""
    @struct.dataclass
    class AdagradState:
        count: jnp.ndarray
        accum: any

    def init(params):
        return AdagradState(count=jnp.zeros((), jnp.int32),
                            accum=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        def leaf(g, acc, p):
            g = g.astype(jnp.float32)
            if weight_decay > 0.0:
                g = g + weight_decay * p
            acc_new = acc + g * g
            return -lr * g / (jnp.sqrt(acc_new) + eps), acc_new

        out = jax.tree.map(leaf, grads, state.accum, params)
        updates = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        accum = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        return updates, AdagradState(count=state.count + 1, accum=accum)

    return Optimizer(init, update)


def _normalize_params(params: dict) -> dict:
    """Map torch-style optimizer params to our kwarg names."""
    p = dict(params)
    if "betas" in p:
        p["betas"] = tuple(p["betas"])
    p.pop("lr", None)  # lr flows through the schedule
    p.pop("torch_adam", None)
    return p


OPTIMIZER_REGISTRY = {
    "adam": lambda p: adam(adamw_mode=bool(p.pop("adam_w_mode", True)), **p),
    "adamw": lambda p: adam(adamw_mode=True, **p),
    "fusedadam": lambda p: adam(adamw_mode=bool(p.pop("adam_w_mode", True)), **p),
    "cpuadam": lambda p: adam(adamw_mode=bool(p.pop("adam_w_mode", True)), **p),
    "lamb": lambda p: lamb(**p),
    "fusedlamb": lambda p: lamb(**p),
    "sgd": lambda p: sgd(**p),
    "adagrad": lambda p: adagrad(**p),
    "cpuadagrad": lambda p: adagrad(**p),
    "onebitadam": lambda p: _onebit("onebit_adam", p),
    "zerooneadam": lambda p: _onebit("zero_one_adam", p),
    "onebitlamb": lambda p: _onebit("onebit_lamb", p),
}


def _onebit(which: str, p):
    from deepspeed_tpu.ops import onebit
    return getattr(onebit, which)(**p)


def normalize_optimizer_key(name: str) -> str:
    """Canonical registry key for a JSON optimizer type (shared with the
    engine's 1-bit-family detection so the two cannot desync)."""
    return name.lower().replace("_", "").replace("deepspeed", "")


ONEBIT_OPTIMIZER_KEYS = frozenset(
    {"onebitadam", "zerooneadam", "onebitlamb"})


def build_optimizer(name: str, params: Optional[dict] = None) -> Optimizer:
    """Build from the JSON optimizer section (engine._configure_basic_optimizer
    analog, runtime/engine.py:1314)."""
    key = normalize_optimizer_key(name)
    if key not in OPTIMIZER_REGISTRY:
        raise ValueError(f"unknown optimizer {name!r}; "
                         f"supported: {sorted(OPTIMIZER_REGISTRY)}")
    return OPTIMIZER_REGISTRY[key](_normalize_params(params or {}))
