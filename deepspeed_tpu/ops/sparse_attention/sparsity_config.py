"""Sparsity layout family.

Own implementation of the reference's ``sparsity_config.py`` pattern zoo
(Dense / Fixed / Variable / BigBird / BSLongformer / LocalSlidingWindow,
``sparsity_config.py:63-743``): each config emits a boolean block layout
``[num_heads, num_blocks, num_blocks]`` (numpy here; the reference uses
torch). Parameter names and layout semantics match the reference so
configs port 1:1; construction is vectorized numpy instead of per-cell
loops.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class SparsityConfig:
    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} must be divisible by "
                             f"block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), np.int64)

    def check_and_propagate_first_head_layout(self,
                                              layout: np.ndarray
                                              ) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks active (sanity/testing pattern)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer 'fixed' pattern (Child et al. 2019): local
    windows of ``num_local_blocks`` + per-window global representative
    blocks (reference ``:94-241``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks:
            raise ValueError("num_local_blocks must be divisible by "
                             "num_global_blocks")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError("uni/bidirectional only")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("horizontal global attention needs "
                             "bidirectional attention")
        if num_different_global_patterns > 1 and \
                not different_layout_per_head:
            raise ValueError("multiple global patterns need "
                             "different_layout_per_head=True")
        if num_different_global_patterns > \
                num_local_blocks // num_global_blocks:
            raise ValueError("num_different_global_patterns too large")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def _local(self, h, layout):
        nb = layout.shape[1]
        for i in range(0, nb, self.num_local_blocks):
            end = min(i + self.num_local_blocks, nb)
            for row in range(i, end):
                stop = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, i:stop] = 1
        return layout

    def _global(self, h, layout):
        nb = layout.shape[1]
        first = self.num_local_blocks - (
            1 + h % self.num_different_global_patterns
        ) * self.num_global_blocks
        end = nb - (nb % self.num_local_blocks)
        for i in range(first, end, self.num_local_blocks):
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + self.num_global_blocks] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + self.num_global_blocks, :] = 1
        if end < nb:   # short last window
            start = min(end + first, nb - self.num_global_blocks)
            stop = start + self.num_global_blocks
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:stop] = 1
            if self.horizontal_global_attention:
                layout[h, start:stop, :] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self._local(h, layout)
            layout = self._global(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Random + local(variable windows) + global columns
    (reference ``:243-420``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional",
                 horizontal_global_attention=False, seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.rng = np.random.RandomState(seed)

    def _random(self, h, layout):
        nb = layout.shape[1]
        if self.num_random_blocks == 0:
            return layout
        if nb < self.num_random_blocks:
            raise ValueError("num_random_blocks exceeds row blocks")
        for row in range(nb):
            hi = nb if self.attention == "bidirectional" else row + 1
            k = min(self.num_random_blocks, hi)
            cols = self.rng.choice(hi, size=k, replace=False)
            layout[h, row, cols] = 1
        return layout

    def _local(self, h, layout):
        nb = layout.shape[1]
        start = 0
        wi = 0
        while start < nb:
            w = self.local_window_blocks[
                min(wi, len(self.local_window_blocks) - 1)]
            end = min(start + w, nb)
            for row in range(start, end):
                stop = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, start:stop] = 1
            start = end
            wi += 1
        return layout

    def _global(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < nb:
                    first_row = 0 if self.attention == "bidirectional" \
                        else idx
                    layout[h, first_row:, idx] = 1
                    if self.horizontal_global_attention:
                        layout[h, idx, :] = 1
        else:
            for s, e in zip(self.global_block_indices,
                            self.global_block_end_indices):
                if s < nb:
                    e = min(e, nb)
                    first_row = 0 if self.attention == "bidirectional" else s
                    layout[h, first_row:, s:e] = 1
                    if self.horizontal_global_attention:
                        layout[h, s:e, :] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self._random(h, layout)
            layout = self._local(h, layout)
            layout = self._global(h, layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding window + global ITC (reference ``:421-557``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError
        self.attention = attention
        self.rng = np.random.RandomState(seed)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < max(self.num_random_blocks,
                    self.num_sliding_window_blocks, self.num_global_blocks):
            raise ValueError("sequence too short for the BigBird pattern")
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for row in range(nb):   # random
                hi = nb if self.attention == "bidirectional" else row + 1
                k = min(self.num_random_blocks, hi)
                layout[h, row, self.rng.choice(hi, k, replace=False)] = 1
            for row in range(nb):   # sliding window
                layout[h, row, max(0, row - w):min(row + w + 1, nb)] = 1
            g = self.num_global_blocks   # global ITC
            layout[h, :g, :] = 1
            layout[h, :, :g] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """sliding window + global rows/cols at given indices
    (reference ``:559-686``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        if global_block_end_indices is not None:
            if len(global_block_end_indices) != \
                    len(self.global_block_indices):
                raise ValueError("global start/end index length mismatch")
            for s, e in zip(self.global_block_indices,
                            global_block_end_indices):
                if s >= e:
                    raise ValueError("global start must be < end")
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError("sequence too short for the window")
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for row in range(nb):
                layout[h, row, max(0, row - w):min(row + w + 1, nb)] = 1
            if self.global_block_end_indices is None:
                for idx in self.global_block_indices:
                    if idx < nb:
                        layout[h, idx, :] = 1
                        layout[h, :, idx] = 1
            else:
                for s, e in zip(self.global_block_indices,
                                self.global_block_end_indices):
                    if s < nb:
                        e = min(e, nb)
                        layout[h, s:e, :] = 1
                        layout[h, :, s:e] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Purely-local sliding window (reference ``:688-743``)."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3,
                 attention="unidirectional"):
        super().__init__(num_heads, block, False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError("sequence too short for the window")
        w = self.num_sliding_window_blocks // 2
        for row in range(nb):
            start = max(0, row - w)
            end = min(row + w + 1, nb) if self.attention == "bidirectional" \
                else row + 1
            layout[0, row, start:end] = 1
        return self.check_and_propagate_first_head_layout(layout)
