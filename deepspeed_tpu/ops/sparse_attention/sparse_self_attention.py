"""SparseSelfAttention front-end.

Analog of ``sparse_self_attention.py`` (+ the BertSparseSelfAttention
wrapper): takes q/k/v and a :class:`SparsityConfig`, caches the layout+LUT
per sequence length, and runs the Pallas block-sparse kernel on TPU (or
the dense-masked XLA oracle elsewhere). The reference's HF model patcher
(``sparse_attention_utils.py``) is torch module surgery — its TPU analog
is passing ``use_sparse_attention`` through the model config (see
models/gpt2.py) rather than editing live modules.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.pallas.block_sparse_attention import (
    block_sparse_attention, build_lut)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig, SparsityConfig)

NEG_INF = -1e30


def layout_to_dense_mask(layout: np.ndarray, block: int,
                         causal: bool) -> np.ndarray:
    """[H, nb, nb] block layout → [H, T, T] element mask (oracle path)."""
    H, nb, _ = layout.shape
    T = nb * block
    mask = np.kron(layout, np.ones((block, block), np.int64)).astype(bool)
    if causal:
        mask &= np.tril(np.ones((T, T), bool))[None]
    return mask


def sparse_attention_reference(q, k, v, layout: np.ndarray, block: int,
                               causal: bool) -> jax.Array:
    """Dense-masked numerics oracle. q/k/v [B, T, H, D]."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    att = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                     k.astype(jnp.float32)) * scale
    mask = jnp.asarray(layout_to_dense_mask(layout, block, causal))
    att = jnp.where(mask[None], att, NEG_INF)
    p = jax.nn.softmax(att, axis=-1)
    # fully-masked rows (no active block) produce zeros like the kernel
    any_active = mask.any(-1)[None, :, :, None]     # [1, H, T, 1]
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return jnp.where(any_active.transpose(0, 2, 1, 3), out,
                     0.0).astype(q.dtype)


def sparse_attention(q, k, v, layout: np.ndarray, block: int,
                     causal: bool = False,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Block-sparse attention. q/k/v ``[B, T, H, D]`` → same shape."""
    lut, counts = build_lut(layout)
    qt = jnp.swapaxes(q, 1, 2)   # [B, H, T, D]
    out = block_sparse_attention(
        qt, jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
        jnp.asarray(lut), jnp.asarray(counts), block=block, causal=causal,
        interpret=interpret)
    return jnp.swapaxes(out, 1, 2)


class SparseSelfAttention:
    """Drop-in sparse attention op (reference ``SparseSelfAttention``).

    >>> op = SparseSelfAttention(FixedSparsityConfig(num_heads=16,
    ...                                              block=128))
    >>> ctx = op(q, k, v)   # [B, T, H, D]
    """

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul"):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(
            num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._cache: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] \
            = {}

    @property
    def causal(self) -> bool:
        return getattr(self.sparsity_config, "attention",
                       "bidirectional") == "unidirectional"

    def layout(self, seq_len: int) -> np.ndarray:
        return self._entry(seq_len)[0]

    def _entry(self, seq_len: int):
        if seq_len not in self._cache:
            lay = self.sparsity_config.make_layout(seq_len)
            lut, counts = build_lut(lay)
            # device-resident once: the per-call host rebuild + transfer
            # is exactly what the reference's LUT cache avoids
            self._cache[seq_len] = (lay, jnp.asarray(lut),
                                    jnp.asarray(counts))
        return self._cache[seq_len]

    def __call__(self, query, key, value, key_padding_mask=None,
                 interpret: Optional[bool] = None):
        B, T, H, D = query.shape
        if H != self.sparsity_config.num_heads:
            raise ValueError(
                f"q has {H} heads but sparsity config was built for "
                f"{self.sparsity_config.num_heads}")
        lay, lut, counts = self._entry(T)
        if key_padding_mask is not None:
            # padded keys masked in the oracle path (reference applies the
            # same inside its softmax kernel)
            scale = 1.0 / (D ** 0.5)
            # bf16 dot inputs, fp32 accumulation (MXU full rate); the
            # fp32-cast form above stays only in the test oracle
            att = jnp.einsum("bqhd,bkhd->bhqk", query, key,
                             preferred_element_type=jnp.float32) * scale
            mask = jnp.asarray(layout_to_dense_mask(
                lay, self.sparsity_config.block, self.causal))[None]
            mask = mask & key_padding_mask[:, None, None, :].astype(bool)
            att = jnp.where(mask, att, NEG_INF)
            p = jax.nn.softmax(att, axis=-1)
            out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(value.dtype),
                             value)
            # fully-masked rows (all keys padded) output zeros, matching
            # the kernel and the oracle — not the uniform-softmax mean(v)
            row_live = mask.any(-1)                       # [B, H, T]
            return jnp.where(jnp.swapaxes(row_live, 1, 2)[..., None],
                             out, 0.0)
        out = block_sparse_attention(
            jnp.swapaxes(query, 1, 2), jnp.swapaxes(key, 1, 2),
            jnp.swapaxes(value, 1, 2), lut, counts,
            block=self.sparsity_config.block, causal=self.causal,
            interpret=interpret)
        return jnp.swapaxes(out, 1, 2)
