"""Block-sparse attention (analog of ``deepspeed/ops/sparse_attention/``).

The reference implements Triton SDD/DSD/DDS block matmuls + fused softmax
(``matmul.py``, ``softmax.py``) driven by block layouts from the
SparsityConfig family, with a C++ LUT builder
(``csrc/sparse_attention/utils.cpp``). On TPU the layout family ports as
pure numpy, the LUT is built host-side (utils.cpp analog), and the kernel
is one Pallas flash-attention variant that iterates only each query
block's active key blocks — the SDD→softmax→DSD chain fused into a single
online-softmax kernel (no block-sparse intermediate ever exists).
"""
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, LocalSlidingWindowSparsityConfig, SparsityConfig,
    VariableSparsityConfig)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention, sparse_attention, sparse_attention_reference)

__all__ = ["SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
           "VariableSparsityConfig", "BigBirdSparsityConfig",
           "BSLongformerSparsityConfig", "LocalSlidingWindowSparsityConfig",
           "SparseSelfAttention", "sparse_attention",
           "sparse_attention_reference"]
