"""Per-axis symmetric int8 quantization core — ONE home for the scale
idiom.

Three subsystems quantize along an axis with a symmetric amax/127 scale:
SwitchBack int8 training (``ops/int8_training.py``, per-token activation
and per-output-column weight scales), the serving weight path
(``ops/int8_gemm.py`` / ``module_inject/quantize.py``, which quantize
against STORED ``{"q", "scale"}`` trees and stay separate on purpose),
and — as of the KV-tiering PR — the int8 paged KV cache
(``inference/kv_cache.py``), whose writers quantize each written token's
``[H, D]`` rows on the fly and whose attention kernels dequantize tiles
in VMEM. This module is the single definition of the
clip/round/zero-amax pattern the first and third share, so a numerics
fix (the zero-amax guard, the 127-not-128 clip) lands once.

Contract (pinned by tests/test_kv_tiering.py round-trip properties):

* ``scale = amax / 127`` along ``axis`` (or one scale for the whole
  tensor when ``axis=None``); an all-zero slice gets scale 1.0 so the
  dequant is exact zero, never 0/0.
* ``q = clip(round(x / scale), -127, 127)`` — symmetric, -128 unused
  (the asymmetric extra level is not worth breaking negation symmetry).
* round-trip error is elementwise bounded by ``scale / 2`` (round-
  to-nearest of an in-range value), i.e. relative to the slice amax the
  error never exceeds ``1/254``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

INT8_QMAX = 127.0


def quantize_int8(x: jax.Array, axis):
    """Symmetric int8 along ``axis`` (int, tuple, or None = one scale
    for the whole tensor): returns ``(q int8, scale f32)`` with the
    scale broadcastable against ``x`` (kept dims of size 1 along
    ``axis`` when ``axis`` is not None)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=axis is not None)
    s = jnp.where(amax > 0, amax / INT8_QMAX, 1.0)
    q = jnp.clip(jnp.round(xf / s), -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    return q, s


def dequantize_int8(q: jax.Array, scale: jax.Array,
                    dtype=jnp.float32) -> jax.Array:
    """``q * scale`` in f32, cast to ``dtype`` — the inverse of
    :func:`quantize_int8` up to the ``scale/2`` rounding bound. The
    multiply fuses into a consuming matmul under XLA, so dequantizing
    at a gather site costs no extra HBM round trip."""
    return (q.astype(jnp.float32) * scale).astype(dtype)
