"""Spatial (diffusers) inference ops.

Analog of ``csrc/spatial/`` (N9: ``nhwc_bias_add``, ``nhwc_bias_add_add``,
``nhwc_bias_add_bias_add`` — ``csrc/spatial/csrc/pt_binding.cpp:108-110``).
The reference hand-fuses these NHWC epilogues because eager torch would
materialize each intermediate; under XLA they are single fused HLO ops —
the value here is keeping the op *surface* so diffusers-style UNet blocks
port against the same names.
"""
from __future__ import annotations

import jax.numpy as jnp


def _check_nhwc(x, bias):
    if x.shape[-1] != bias.shape[-1]:
        raise ValueError(
            f"channel-last bias: activation C={x.shape[-1]} vs bias "
            f"C={bias.shape[-1]}")


def nhwc_bias_add(activation, bias):
    """y = x + b (broadcast over N, H, W)."""
    _check_nhwc(activation, bias)
    return activation + bias.astype(activation.dtype)


def nhwc_bias_add_add(activation, bias, other):
    """y = (x + b) + other."""
    _check_nhwc(activation, bias)
    if other.shape != activation.shape:
        raise ValueError(f"residual shape {other.shape} != "
                         f"{activation.shape}")
    return activation + bias.astype(activation.dtype) + \
        other.astype(activation.dtype)


def nhwc_bias_add_bias_add(activation, bias, other, other_bias):
    """y = (x + b) + (other + ob)."""
    _check_nhwc(activation, bias)
    _check_nhwc(other, other_bias)
    return (activation + bias.astype(activation.dtype) +
            other.astype(activation.dtype) +
            other_bias.astype(activation.dtype))
