"""Attention ops with hardware dispatch.

The hot-path analog of the reference's fused attention kernels
(``csrc/transformer/softmax_kernels.cu`` for training,
``softmax_context`` in ``csrc/transformer/inference/csrc/pt_binding.cpp``
for decode). On TPU the MXU does the matmuls; the win is avoiding the
O(T²) attention-matrix round-trip to HBM — a Pallas flash-attention kernel
(deepspeed_tpu/ops/pallas/flash_attention.py) on TPU, with a pure-jnp
reference path on CPU (used by the unit tests and as the numerics oracle).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def causal_attention_reference(q, k, v, scale=None, causal=True):
    """Numerics oracle: plain softmax attention, fp32 accumulation.

    Shapes: q ``[B, T, H, D]`` → ``[B, T, H, D]``; k/v may carry fewer
    heads (``[B, T, HKV, D]``, HKV | H — grouped-query attention,
    broadcast per query group without materializing repeated k/v). Also
    serves the sequence-parallel modes' dense core and degenerate-mesh
    fallbacks, so scale/causal overrides live HERE, once.
    """
    B, T, H, D = q.shape
    HKV = k.shape[2]
    if H % HKV:
        raise ValueError(f"q heads {H} not divisible by kv heads {HKV}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    # one body serves MHA (g=1) and GQA: the group axis broadcasts k/v per
    # query group without materializing repeats, and XLA drops the
    # degenerate axis for plain attention
    g = H // HKV
    q5 = q.reshape(B, T, HKV, g, D)
    att = (jnp.einsum("bqhgd,bkhd->bhgqk", q5, k).astype(jnp.float32)
           * scale)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        att = jnp.where(mask[None, None, None], att, -1e30)
    att = jax.nn.softmax(att, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", att.astype(v.dtype), v)
    return out.reshape(B, T, H, D)


def causal_attention(q, k, v, block_q: int = 0, block_k: int = 0):
    """Causal self-attention ``[B, T, H, D] -> [B, T, H, D]``; k/v may
    carry fewer heads (grouped-query attention — both the flash kernel
    and the reference path consume unexpanded k/v). ``block_q/block_k``
    override the flash kernel's tile sizes (0 = kernel default) — the
    long-context block-size A/B knob (docs/mfu_analysis.md).

    The flash output is tagged with ``checkpoint_name('flash_attn_out')``:
    under ``jax.checkpoint`` the dots-saveable remat policy cannot see
    inside the kernel's custom_vjp, so without the tag the whole flash
    forward would re-run during backward — measured as a net train-step
    LOSS vs unfused attention at seq 1024 despite the kernel itself being
    several times faster. Models extend their policy with
    ``save_only_these_names('flash_attn_out')`` (models/gpt2.py).
    """
    if _on_tpu() and q.shape[1] >= 256:
        try:
            from deepspeed_tpu.ops.pallas.flash_attention import (
                DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, flash_attention)
        except ImportError:
            from deepspeed_tpu.utils.logging import warning_once
            warning_once("pallas flash attention unavailable; falling back to "
                         "O(T^2) reference attention")
        else:
            from jax.ad_checkpoint import checkpoint_name
            return checkpoint_name(
                flash_attention(q, k, v, causal=True,
                                block_q=block_q or DEFAULT_BLOCK_Q,
                                block_k=block_k or DEFAULT_BLOCK_K),
                "flash_attn_out")
    return causal_attention_reference(q, k, v)
