"""Async file IO handle (analog of ``deepspeed/ops/aio`` over csrc/aio).

Reads/writes numpy buffers against swap files on a C++ thread pool; the
Python thread returns immediately and synchronizes with ``wait()`` —
the reference's ``aio_handle`` semantics (csrc/aio/py_lib/py_ds_aio.cpp).
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import AsyncIOBuilder


class AsyncIOHandle:
    def __init__(self, num_threads: int = 4):
        self._lib = AsyncIOBuilder().load()
        self._h = self._lib.dstpu_aio_create(num_threads)
        if not self._h:
            raise RuntimeError("failed to create aio handle")

    def pwrite(self, path: str, buf: np.ndarray, offset: int = 0) -> None:
        assert buf.flags["C_CONTIGUOUS"]
        self._keepalive = getattr(self, "_keepalive", [])
        self._keepalive.append(buf)   # pin until wait()
        self._lib.dstpu_aio_pwrite(self._h, os.fsencode(path),
                                   buf.ctypes.data_as(ctypes.c_void_p),
                                   buf.nbytes, offset)

    def pread(self, path: str, buf: np.ndarray, offset: int = 0) -> None:
        assert buf.flags["C_CONTIGUOUS"] and buf.flags["WRITEABLE"]
        self._keepalive = getattr(self, "_keepalive", [])
        self._keepalive.append(buf)
        self._lib.dstpu_aio_pread(self._h, os.fsencode(path),
                                  buf.ctypes.data_as(ctypes.c_void_p),
                                  buf.nbytes, offset)

    def wait(self) -> int:
        """Block until all pending requests finish; returns error count."""
        errs = int(self._lib.dstpu_aio_wait(self._h))
        self._keepalive = []
        return errs

    def close(self):
        if getattr(self, "_h", None):
            self.wait()
            self._lib.dstpu_aio_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:
            pass
