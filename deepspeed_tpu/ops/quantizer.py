"""Groupwise quantization ops.

Analog of the reference quantizer kernels (``csrc/quantization/`` N6:
``ds_quantize_{fp32,fp16}``, ``ds_sr_quantize*``, ``*_asym*`` —
``pt_binding.cpp:149-168``) and the python wrapper
(``deepspeed/ops/quantizer/quantizer.py``). These are bandwidth-bound
elementwise ops that XLA fuses into adjacent producers/consumers on TPU, so
the implementation is jnp; the semantics (groupwise symmetric/asymmetric,
stochastic rounding variants) match the reference op surface.

All functions quantize a flat trailing dimension per group: the input is
reshaped to ``[groups, -1]`` exactly like the CUDA kernels' block-per-group
layout.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _grouped(x: jax.Array, groups: int) -> jax.Array:
    if x.size % groups:
        raise ValueError(f"size {x.size} not divisible by groups {groups}")
    return x.reshape(groups, -1)


def quantize_symmetric(x: jax.Array, groups: int, bits: int = 8,
                       rng: Optional[jax.Array] = None
                       ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric groupwise quantization → (int8 values, fp32 scales).

    ``rng`` enables stochastic rounding (the reference's ``ds_sr_quantize``).
    """
    orig_shape = x.shape
    g = _grouped(x.astype(jnp.float32), groups)
    qmax = float(2 ** (bits - 1) - 1)
    absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    scaled = g / scale
    if rng is not None:
        noise = jax.random.uniform(rng, scaled.shape) - 0.5
        q = jnp.floor(scaled + 0.5 + noise)
    else:
        q = jnp.round(scaled)
    q = jnp.clip(q, -qmax - 1, qmax).astype(jnp.int8)
    return q.reshape(orig_shape), scale[:, 0]


def quantize_asymmetric(x: jax.Array, groups: int, bits: int = 8,
                        rng: Optional[jax.Array] = None
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Asymmetric groupwise quantization → (int8 values, scales, zero points)
    (reference ``ds_quantize_asym`` family)."""
    orig_shape = x.shape
    g = _grouped(x.astype(jnp.float32), groups)
    qrange = float(2 ** bits - 1)
    gmin = jnp.min(g, axis=1, keepdims=True)
    gmax = jnp.max(g, axis=1, keepdims=True)
    scale = jnp.where(gmax > gmin, (gmax - gmin) / qrange, 1.0)
    zero = gmin
    scaled = (g - zero) / scale
    if rng is not None:
        noise = jax.random.uniform(rng, scaled.shape) - 0.5
        q = jnp.floor(scaled + 0.5 + noise)
    else:
        q = jnp.round(scaled)
    q = (q - 2 ** (bits - 1)).astype(jnp.int8)
    return q.reshape(orig_shape), scale[:, 0], zero[:, 0]


def dequantize_symmetric(q: jax.Array, scale: jax.Array, groups: int,
                         dtype=jnp.float32) -> jax.Array:
    orig_shape = q.shape
    g = _grouped(q.astype(jnp.float32), groups)
    return (g * scale[:, None]).astype(dtype).reshape(orig_shape)


def dequantize_asymmetric(q: jax.Array, scale: jax.Array, zero: jax.Array,
                          groups: int, dtype=jnp.float32) -> jax.Array:
    orig_shape = q.shape
    g = _grouped(q.astype(jnp.float32), groups)
    bits_half = 128.0  # int8 storage offset used by quantize_asymmetric
    return ((g + bits_half) * scale[:, None] +
            zero[:, None]).astype(dtype).reshape(orig_shape)


def fake_quantize(x: jax.Array, groups: int, bits: int = 8,
                  symmetric: bool = True,
                  rng: Optional[jax.Array] = None) -> jax.Array:
    """Quantize→dequantize in one step (reference ``fake_quantizer.cu`` —
    used by MoQ quantize-aware training, runtime/quantize.py)."""
    if symmetric:
        q, scale = quantize_symmetric(x, groups, bits, rng)
        return dequantize_symmetric(q, scale, groups, x.dtype)
    q, scale, zero = quantize_asymmetric(x, groups, bits, rng)
    return dequantize_asymmetric(q, scale, zero, groups, x.dtype)


class Quantizer:
    """Object API mirroring ``deepspeed.ops.quantizer.ds_quantizer``
    (ops/quantizer/quantizer.py:1-29)."""

    def __init__(self, q_bits: int = 8, q_groups: int = 1,
                 symmetric: bool = True, stochastic: bool = False):
        self.q_bits = q_bits
        self.q_groups = q_groups
        self.symmetric = symmetric
        self.stochastic = stochastic

    def quantize(self, x, rng=None):
        rng = rng if self.stochastic else None
        if self.symmetric:
            return quantize_symmetric(x, self.q_groups, self.q_bits, rng)
        return quantize_asymmetric(x, self.q_groups, self.q_bits, rng)

    def fake_quantize(self, x, rng=None):
        return fake_quantize(x, self.q_groups, self.q_bits, self.symmetric,
                             rng if self.stochastic else None)
