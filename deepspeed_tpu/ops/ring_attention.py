"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference snapshot has NO sequence parallelism (SURVEY §5.7: predates
DeepSpeed-Ulysses/ring attention; long sequences are handled there by
block-sparse attention and activation partitioning). This module is the
TPU-first capability the new framework adds: Q/K/V stay sharded over the
``seq`` axis, K/V shards circulate the ring via ``lax.ppermute`` (ICI
neighbour hops), and each device folds every visiting block into a running
online-softmax state — attention over the FULL sequence with per-device
memory O(T/sp) and no all-gather.

Backward is a second ring pass: dK/dV accumulators circulate WITH their K/V
shards so each shard collects every rank's contribution and arrives home
complete; dQ accumulates locally. Both passes are wired through
``jax.custom_vjp`` (the scan-of-ppermute forward would otherwise stash every
visiting block).

Causal masking uses global positions (q_global >= k_global), so ranks
holding future K/V blocks contribute fully-masked (zero) terms — the
classic ring-attention load imbalance; a striped layout is future work.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.mesh import get_global_mesh

NEG_INF = -1e30
SEQ_AXIS = "seq"


def _block_scores(q5, k, scale, q_start, k_start, causal):
    """Masked scores s ``[B, HKV, G, Tq, Tk]`` in fp32 plus the bool mask.

    ``q5`` is the query block in grouped layout ``[B, Tq, HKV, G, D]``
    (G = n_head / n_kv_head; G=1 for plain MHA) against an UNEXPANDED
    k ``[B, Tk, HKV, D]`` — grouped-query attention's k/v stay at their
    native head count through every ring hop, so GQA's ICI-bandwidth
    saving survives sequence parallelism. Inputs stay in their storage
    dtype (bf16) so the MXU runs at full rate; fp32 comes from the
    accumulator (preferred_element_type), the same fix as the Pallas
    flash kernels."""
    s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = q5.shape[1], k.shape[1]
        qpos = q_start + jnp.arange(Tq)
        kpos = k_start + jnp.arange(Tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None, None], s, NEG_INF)
        return s, mask[None, None, None]
    return s, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention(q, k, v, axis_name, causal, scale):
    o, _ = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return o


def _varying(x, axis_name):
    """Mark a carry init as device-varying over the ring axis (vma typing)."""
    try:
        return jax.lax.pcast(x, (axis_name,), to="varying")
    except (AttributeError, TypeError):
        return x


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    sp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    HKV = k.shape[2]
    G = H // HKV
    q5 = q.reshape(B, Tl, HKV, G, D)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    m = _varying(jnp.full((B, HKV, G, Tl, 1), NEG_INF, jnp.float32),
                 axis_name)
    l = _varying(jnp.zeros((B, HKV, G, Tl, 1), jnp.float32), axis_name)
    acc = _varying(jnp.zeros((B, Tl, HKV, G, D), jnp.float32), axis_name)
    q_start = idx * Tl

    def step_fn(carry, step):
        m, l, acc, k_cur, v_cur = carry
        src = (idx - step) % sp

        def attend(mla):
            m, l, acc = mla
            s, mask = _block_scores(q5, k_cur, scale, q_start, src * Tl,
                                    causal)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            if mask is not None:
                p = p * mask
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * jnp.moveaxis(alpha, 3, 1) + jnp.einsum(
                "bhgqk,bkhd->bqhgd", p.astype(v_cur.dtype), v_cur,
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        if causal:
            # hops whose K/V block sits entirely ABOVE the diagonal
            # (src > idx) contribute exactly nothing (p ≡ 0): skip the
            # whole score/softmax/einsum — on average half the ring's
            # attention FLOPs. The ppermutes stay unconditional (every
            # device must participate in every hop's collective).
            m, l, acc = jax.lax.cond(src <= idx, attend,
                                     lambda mla: mla, (m, l, acc))
        else:
            m, l, acc = attend((m, l, acc))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        step_fn, (m, l, acc, k, v), jnp.arange(sp))
    l_safe = jnp.maximum(l, 1e-30)
    o = (acc / jnp.moveaxis(l_safe, 3, 1)).astype(q.dtype)
    o = o.reshape(B, Tl, H, D)
    lse = m + jnp.log(l_safe)  # [B, HKV, G, Tl, 1]
    return o, lse


def _ring_fwd(q, k, v, axis_name, causal, scale):
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return o, (q, k, v, o, lse)


def _ring_bwd(axis_name, causal, scale, res, do):
    q, k, v, o, lse = res
    sp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    HKV = k.shape[2]
    G = H // HKV
    q5 = q.reshape(B, Tl, HKV, G, D)
    do5 = do.reshape(B, Tl, HKV, G, D)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    do32 = do5.astype(jnp.float32)
    delta = jnp.sum(do32 * o.astype(jnp.float32).reshape(do5.shape),
                    axis=-1)  # [B, Tl, HKV, G]
    delta = jnp.moveaxis(delta, 1, 3)[..., None]  # [B, HKV, G, Tl, 1]
    q_start = idx * Tl

    dq = _varying(jnp.zeros(q5.shape, jnp.float32), axis_name)
    # dk/dv accumulate (and ride the ring) at the UNEXPANDED head count:
    # the einsums below sum each kv head's query group, so GQA's hop
    # traffic shrinks by G in backward too
    dk0 = _varying(jnp.zeros(k.shape, jnp.float32), axis_name)
    dv0 = _varying(jnp.zeros(v.shape, jnp.float32), axis_name)

    def step_fn(carry, step):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (idx - step) % sp

        def attend(grads):
            dq, dk_cur, dv_cur = grads
            s, mask = _block_scores(q5, k_cur, scale, q_start, src * Tl,
                                    causal)
            p = jnp.exp(s - lse)
            if mask is not None:
                p = p * mask
            # dv += p^T do ; ds = p*(dp - delta); dk += ds^T q ; dq += ds k
            dv_new = dv_cur + jnp.einsum(
                "bhgqk,bqhgd->bkhd", p.astype(do.dtype), do5,
                preferred_element_type=jnp.float32)
            dp = jnp.einsum("bqhgd,bkhd->bhgqk", do5, v_cur,
                            preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            ds16 = ds.astype(q.dtype)
            dk_new = dk_cur + jnp.einsum(
                "bhgqk,bqhgd->bkhd", ds16, q5,
                preferred_element_type=jnp.float32) * scale
            dq_new = dq + jnp.einsum(
                "bhgqk,bkhd->bqhgd", ds16, k_cur,
                preferred_element_type=jnp.float32) * scale
            return dq_new, dk_new, dv_new

        if causal:
            # fully-above-diagonal hops have p ≡ 0 ⇒ every grad term is
            # zero: skip them (same skip as forward; collectives stay out)
            dq, dk_cur, dv_cur = jax.lax.cond(
                src <= idx, attend, lambda g: g, (dq, dk_cur, dv_cur))
        else:
            dq, dk_cur, dv_cur = attend((dq, dk_cur, dv_cur))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (dq, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step_fn, (dq, k, v, dk0, dv0), jnp.arange(sp))
    # after sp hops the accumulators are back at their home rank
    return (dq.reshape(q.shape).astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


_ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_sharded(q, k, v, axis_name: str = SEQ_AXIS,
                           causal: bool = True,
                           scale: Optional[float] = None):
    """Call INSIDE a shard_map manual over ``axis_name``.

    q/k/v: per-device sequence shards ``[B, T/sp, H, D]``.
    """
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"q heads {q.shape[2]} not divisible by kv "
                         f"heads {k.shape[2]}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _ring_attention(q, k, v, axis_name, causal, float(scale))


def ring_self_attention(q, k, v, mesh: Optional[Mesh] = None,
                        causal: bool = True,
                        scale: Optional[float] = None):
    """Global-array entry point: shards [B, T, H, D] over the ``seq`` axis
    and runs the ring. Works inside jit (other mesh axes stay automatic)."""
    mesh = mesh or get_global_mesh()
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"q heads {q.shape[2]} not divisible by kv "
                         f"heads {k.shape[2]}")
    if SEQ_AXIS not in mesh.axis_names or mesh.shape[SEQ_AXIS] == 1:
        from deepspeed_tpu.ops.attention import causal_attention_reference
        return causal_attention_reference(q, k, v, scale=scale,
                                          causal=causal)
    sp = mesh.shape[SEQ_AXIS]
    if q.shape[1] % sp:
        raise ValueError(f"seq len {q.shape[1]} not divisible by seq "
                         f"axis {sp}")
    fn = functools.partial(ring_attention_sharded, causal=causal, scale=scale)
    spec = P(None, SEQ_AXIS, None, None)
    # check_vma must stay ON: axis_index under partial-manual shard_map
    # needs the varying-manual-axes tracking to type-check
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={SEQ_AXIS})(q, k, v)
