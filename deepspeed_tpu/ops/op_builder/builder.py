"""JIT g++ builder + ctypes loader for the host-side native ops.

Analog of ``op_builder/builder.py``: ``load()`` returns a bound module,
building on first use into a content-hashed cache dir
(``~/.cache/deepspeed_tpu_ops`` or ``$DSTPU_EXTENSIONS_DIR`` — the
``TORCH_EXTENSIONS_DIR`` analog). ``is_compatible()`` gates tests the way
the reference skips unbuildable CUDA ops.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
from pathlib import Path
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = Path(__file__).resolve().parents[3]


def _cache_dir() -> Path:
    d = os.environ.get("DSTPU_EXTENSIONS_DIR",
                       os.path.expanduser("~/.cache/deepspeed_tpu_ops"))
    p = Path(d)
    p.mkdir(parents=True, exist_ok=True)
    return p


class OpBuilder:
    name: str = "base"
    sources: List[str] = []          # relative to repo csrc/
    extra_flags: List[str] = []

    _loaded: Dict[str, ctypes.CDLL] = {}

    def compiler(self) -> Optional[str]:
        return shutil.which("g++") or shutil.which("c++")

    def is_compatible(self) -> bool:
        return self.compiler() is not None and all(
            (_REPO_ROOT / "csrc" / s).is_file() for s in self.sources)

    def _source_paths(self) -> List[Path]:
        return [_REPO_ROOT / "csrc" / s for s in self.sources]

    def _hash(self) -> str:
        h = hashlib.sha256()
        for p in self._source_paths():
            h.update(p.read_bytes())
        h.update(" ".join(self.extra_flags).encode())
        return h.hexdigest()[:16]

    def load(self) -> ctypes.CDLL:
        """Build (if needed) and dlopen the op library."""
        if self.name in OpBuilder._loaded:
            return OpBuilder._loaded[self.name]
        so = _cache_dir() / f"{self.name}-{self._hash()}.so"
        if not so.is_file():
            cxx = self.compiler()
            if cxx is None:
                raise RuntimeError(f"no C++ compiler for op {self.name}")
            tmp = f"{so}.{os.getpid()}.tmp"   # unique per process: two
            # concurrent first-use builds must not clobber one tmp file
            cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC",
                   "-march=native", "-fopenmp",
                   *self.extra_flags,
                   *[str(p) for p in self._source_paths()],
                   "-o", tmp]
            logger.info(f"building native op {self.name}: {' '.join(cmd)}")
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True)
            except subprocess.CalledProcessError as e:
                # -march=native / -fopenmp may be unsupported: retry plain
                cmd = [c for c in cmd
                       if c not in ("-march=native", "-fopenmp")]
                try:
                    subprocess.run(cmd, check=True, capture_output=True,
                                   text=True)
                except subprocess.CalledProcessError as e2:
                    raise RuntimeError(
                        f"failed to build {self.name}:\n{e.stderr}\n"
                        f"{e2.stderr}") from e2
            os.replace(tmp, so)
        lib = ctypes.CDLL(str(so))
        self._bind(lib)
        OpBuilder._loaded[self.name] = lib
        return lib

    def _bind(self, lib: ctypes.CDLL) -> None:
        """Set argtypes/restype on the exported functions."""


c_f32p = ctypes.POINTER(ctypes.c_float)
c_u16p = ctypes.POINTER(ctypes.c_uint16)
c_i64 = ctypes.c_int64
c_f32 = ctypes.c_float


class CPUAdamBuilder(OpBuilder):
    """csrc/adam/cpu_adam.cpp analog (op_builder/cpu_adam.py)."""
    name = "cpu_adam"
    sources = ["cpu_adam.cpp"]

    def _bind(self, lib):
        lib.dstpu_adam_update.argtypes = [
            c_f32p, c_f32p, c_f32p, c_f32p, c_i64, c_i64, c_f32, c_f32,
            c_f32, c_f32, c_f32, ctypes.c_int, c_u16p]
        lib.dstpu_adam_update.restype = None
        lib.dstpu_adagrad_update.argtypes = [
            c_f32p, c_f32p, c_f32p, c_i64, c_f32, c_f32, c_f32, c_u16p]
        lib.dstpu_adagrad_update.restype = None
        lib.dstpu_simd_width.restype = ctypes.c_int
        lib.dstpu_num_threads.restype = ctypes.c_int


class AsyncIOBuilder(OpBuilder):
    """csrc/aio analog (op_builder/async_io.py)."""
    name = "async_io"
    sources = ["aio.cpp"]

    def _bind(self, lib):
        lib.dstpu_aio_create.argtypes = [ctypes.c_int]
        lib.dstpu_aio_create.restype = ctypes.c_void_p
        lib.dstpu_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_destroy.restype = None
        for fn in (lib.dstpu_aio_pwrite, lib.dstpu_aio_pread):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_void_p, c_i64, c_i64]
            fn.restype = None
        lib.dstpu_aio_wait.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_wait.restype = c_i64


ALL_OPS = {b.name: b for b in (CPUAdamBuilder(), AsyncIOBuilder())}
