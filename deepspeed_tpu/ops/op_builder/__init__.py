"""Native op build system (analog of ``op_builder/``).

The reference JIT-builds CUDA extensions via torch ``cpp_extension.load``
(``op_builder/builder.py:452,464``) with an ``ALL_OPS`` registry
(``all_ops.py:31``). Here native ops are host-side C++ (TPU device code is
Pallas, which needs no build step): g++ compiles ``csrc/*.cpp`` into cached
shared objects bound via ctypes.
"""
from deepspeed_tpu.ops.op_builder.builder import (ALL_OPS, CPUAdamBuilder,
                                                  AsyncIOBuilder, OpBuilder)

__all__ = ["OpBuilder", "CPUAdamBuilder", "AsyncIOBuilder", "ALL_OPS"]
