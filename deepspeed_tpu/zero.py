"""User-facing ZeRO context APIs: ``zero.Init`` and ``GatheredParameters``.

Analog of ``runtime/zero/partition_parameters.py`` ``Init`` (:537) and
``GatheredParameters`` (:1512). The reference hijacks ``nn.Module``
construction so every parameter partitions the moment it is created, and
gives users a context that temporarily allgathers partitioned params for
surgery. Under single-controller JAX the engine already shards params by
construction (``runtime/engine.py _init_state`` — the ``zero.Init``
*mechanism* is a jit with ``out_shardings``), so these contexts are thin
and explicit rather than import-time monkeypatches:

* :class:`Init` — a context that provides the target sharding for
  freshly created params; ``init.shard(tree)`` places a tree with the
  engine's ZeRO-3 policy without ever materializing it replicated on one
  device (the reference's memory-at-construction win).
* :class:`GatheredParameters` — yields full (host numpy) values of the
  selected engine params for in-place surgery; modified values are
  re-placed with their original shardings on exit (``modifier_rank``
  semantics collapse on a single controller: there is one writer).
"""
from __future__ import annotations

from typing import Any, Iterable, Optional

import jax
import numpy as np

from deepspeed_tpu.comm.mesh import build_mesh, get_global_mesh
from deepspeed_tpu.utils.tree import flatten_with_names


class Init:
    """``with zero.Init(config_dict_or_stage) as zinit: params =
    zinit.shard(make_params())`` — params land sharded-by-construction."""

    def __init__(self, config_dict_or_path: Any = None, mesh=None,
                 zero_stage: int = 3, **_):
        from deepspeed_tpu.runtime.zero.partition import ZeroShardingPolicy
        if isinstance(config_dict_or_path, str):
            import json
            with open(config_dict_or_path) as f:
                config_dict_or_path = json.load(f)
        if isinstance(config_dict_or_path, dict):
            zero_stage = config_dict_or_path.get(
                "zero_optimization", {}).get("stage", zero_stage)
        self.mesh = mesh or get_global_mesh()
        self.policy = ZeroShardingPolicy(zero_stage, self.mesh)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def shard(self, params: Any) -> Any:
        """Place a param tree with the ZeRO policy's shardings."""
        return jax.device_put(params, self.policy.param_sharding(params))


class GatheredParameters:
    """``with GatheredParameters(engine, ["wte", "h/0/attn"]) as g:``
    exposes ``g[name]`` as mutable host numpy; writes re-shard on exit.
    Paths are the '/'-joined leaf names of ``flatten_with_names`` — an
    entry selects its exact leaf or every leaf under it as a prefix.
    ``params=None`` gathers every leaf (small models only — the point of
    the reference context is to gather a FEW params briefly)."""

    def __init__(self, engine, params: Optional[Iterable[str]] = None,
                 modifier_rank: Optional[int] = 0, fwd_module=None,
                 enabled: bool = True):
        self.engine = engine
        self.enabled = enabled
        self.paths = list(params) if params is not None else None
        self._host: dict = {}
        self._shardings: dict = {}

    def __enter__(self):
        if not self.enabled:
            return self
        leaves = flatten_with_names(self.engine.state.params)
        sh = flatten_with_names(self.engine._state_shardings.params)
        for name, leaf in leaves.items():
            if self.paths is not None and not any(
                    name == p or name.startswith(p + "/")
                    for p in self.paths):
                continue
            self._host[name] = np.array(jax.device_get(leaf))
            self._shardings[name] = sh[name]
        return self

    def __getitem__(self, name: str) -> np.ndarray:
        return self._host[name]

    def keys(self):
        return self._host.keys()

    def __exit__(self, exc_type, *exc):
        if exc_type is not None or not self.enabled:
            return False
        leaves = flatten_with_names(self.engine.state.params)
        updated = dict(leaves)
        for name, arr in self._host.items():
            updated[name] = jax.device_put(
                arr.astype(leaves[name].dtype), self._shardings[name])
        treedef = jax.tree_util.tree_structure(self.engine.state.params)
        self.engine.state = self.engine.state.replace(
            params=jax.tree_util.tree_unflatten(
                treedef, [updated[k] for k in leaves]))
        return False
