"""Monitoring fan-out — analog of ``deepspeed/monitor/monitor.py:24``
(MonitorMaster → TensorBoard/WandB/CSV writers). Events are
``(name, value, global_sample_count)`` triples exactly as the engine emits
them (runtime/engine.py:1946). The engine routes the same events through
the telemetry registry (``RegistryMonitor``) so they are scrapeable even
with every backend here disabled — MonitorMaster is one sink of several
(docs/observability.md)."""
from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import jax

from deepspeed_tpu.telemetry.registry import (MetricRegistry, get_registry,
                                              sanitize_metric_name)
from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = False

    def write_events(self, event_list: List[Event]):
        raise NotImplementedError

    def close(self):
        """Release file handles / writers; safe to call twice. Backends
        that hold nothing inherit the no-op."""


class CsvMonitor(Monitor):
    def __init__(self, csv_config):
        self.enabled = csv_config.enabled and jax.process_index() == 0
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        self._files = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name),
                        exist_ok=True)

    def _file(self, name):
        if name not in self._files:
            safe = name.replace("/", "_")
            path = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
            f = open(path, "a", newline="")
            self._files[name] = (f, csv.writer(f))
        return self._files[name]

    def write_events(self, event_list: List[Event]):
        if not self.enabled:
            return
        for name, value, step in event_list:
            f, writer = self._file(name)
            writer.writerow([step, value])
            f.flush()

    def close(self):
        # handles reopen on the next write (append mode), so close() at
        # engine teardown cannot strand a later flush
        for f, _ in self._files.values():
            f.close()
        self._files = {}


class TensorBoardMonitor(Monitor):
    def __init__(self, tb_config):
        self.enabled = tb_config.enabled and jax.process_index() == 0
        self.summary_writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
                path = os.path.join(tb_config.output_path or "./runs",
                                    tb_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=path)
            except Exception as e:
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        self.summary_writer.flush()

    def close(self):
        if self.summary_writer is not None:
            try:
                self.summary_writer.close()
            except Exception as e:  # noqa: BLE001 — teardown must not raise
                logger.warning(f"tensorboard close failed: {e}")
            self.summary_writer = None
            self.enabled = False


class WandbMonitor(Monitor):
    def __init__(self, wandb_config):
        self.enabled = wandb_config.enabled and jax.process_index() == 0
        self._wandb = None
        if self.enabled:
            try:
                import wandb
                wandb.init(project=wandb_config.project,
                           group=wandb_config.group, entity=wandb_config.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)

    def close(self):
        if self._wandb is not None:
            try:
                self._wandb.finish()
            except Exception as e:  # noqa: BLE001
                logger.warning(f"wandb finish failed: {e}")
            self._wandb = None
            self.enabled = False


class RegistryMonitor(Monitor):
    """Sink that lands monitor events in the telemetry registry: each
    event name becomes a gauge (``Train/Samples/train_loss`` →
    ``train_samples_train_loss``), the sample clock lands in
    ``train_samples`` — so a scraper sees training step metrics with
    zero backend configuration. The four core train-step scalars are
    ALSO published under canonical short names (``train_loss``,
    ``train_grad_norm``, ``train_lr``, ``train_loss_scale``) so
    dashboards don't have to know the reference's ``Train/Samples/...``
    event spelling."""

    def __init__(self, registry: Optional[MetricRegistry] = None):
        self.registry = registry or get_registry()
        self.enabled = True

    def _canonical(self, name: str, value: float) -> None:
        # spelled out per name (not a loop over a mapping) so the
        # metric-catalog gate (scripts/check_metric_docs.py) can
        # resolve every registration statically
        if name == "Train/Samples/train_loss":
            self.registry.gauge(
                "train_loss",
                help="mean loss of the last reported train step").set(value)
        elif name == "Train/Samples/lr":
            self.registry.gauge(
                "train_lr",
                help="learning rate at the last reported train step"
            ).set(value)
        elif name == "Train/Samples/loss_scale":
            self.registry.gauge(
                "train_loss_scale",
                help="fp16 dynamic loss scale at the last reported "
                     "train step").set(value)
        elif name == "Train/Samples/grad_norm":
            self.registry.gauge(
                "train_grad_norm",
                help="global (pre-clip) gradient norm of the last "
                     "reported train step").set(value)

    def write_events(self, event_list: List[Event]):
        for name, value, step in event_list:
            self.registry.gauge(
                sanitize_metric_name(name),
                help=f"monitor event {name!r} (runtime/engine.py)"
            ).set(float(value))
            self._canonical(name, float(value))
            self.registry.gauge(
                "train_samples",
                help="global sample count at the last monitor event"
            ).set(float(step))


class MonitorMaster(Monitor):
    def __init__(self, ds_config):
        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb_monitor = WandbMonitor(ds_config.wandb)
        self.csv_monitor = CsvMonitor(ds_config.csv_monitor)
        self.monitors = [self.tb_monitor, self.wandb_monitor,
                         self.csv_monitor]
        self.enabled = any(m.enabled for m in self.monitors)

    def write_events(self, event_list: List[Event]):
        for m in self.monitors:
            if m.enabled:
                m.write_events(event_list)

    def close(self):
        for m in self.monitors:
            m.close()

    def __enter__(self) -> "MonitorMaster":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
