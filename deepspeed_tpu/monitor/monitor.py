"""Monitoring fan-out — analog of ``deepspeed/monitor/monitor.py:24``
(MonitorMaster → TensorBoard/WandB/CSV writers). Events are
``(name, value, global_sample_count)`` triples exactly as the engine emits
them (runtime/engine.py:1946)."""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = False

    def write_events(self, event_list: List[Event]):
        raise NotImplementedError


class CsvMonitor(Monitor):
    def __init__(self, csv_config):
        self.enabled = csv_config.enabled and jax.process_index() == 0
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        self._files = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name),
                        exist_ok=True)

    def _file(self, name):
        if name not in self._files:
            safe = name.replace("/", "_")
            path = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
            f = open(path, "a", newline="")
            self._files[name] = (f, csv.writer(f))
        return self._files[name]

    def write_events(self, event_list: List[Event]):
        if not self.enabled:
            return
        for name, value, step in event_list:
            f, writer = self._file(name)
            writer.writerow([step, value])
            f.flush()


class TensorBoardMonitor(Monitor):
    def __init__(self, tb_config):
        self.enabled = tb_config.enabled and jax.process_index() == 0
        self.summary_writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
                path = os.path.join(tb_config.output_path or "./runs",
                                    tb_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=path)
            except Exception as e:
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, wandb_config):
        self.enabled = wandb_config.enabled and jax.process_index() == 0
        if self.enabled:
            try:
                import wandb
                wandb.init(project=wandb_config.project,
                           group=wandb_config.group, entity=wandb_config.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class MonitorMaster(Monitor):
    def __init__(self, ds_config):
        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb_monitor = WandbMonitor(ds_config.wandb)
        self.csv_monitor = CsvMonitor(ds_config.csv_monitor)
        self.enabled = (self.tb_monitor.enabled or self.wandb_monitor.enabled
                        or self.csv_monitor.enabled)

    def write_events(self, event_list: List[Event]):
        for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            if m.enabled:
                m.write_events(event_list)
