"""Compression scheduler (analog of ``deepspeed/compression/scheduler.py``):
tracks which techniques are live at the current step and exposes the
verbose one-shot logging the reference does when a technique activates."""
from __future__ import annotations

from typing import Dict, List

from deepspeed_tpu.compression.compress import (CompressionSpec,
                                                _current_bits)
from deepspeed_tpu.utils.logging import logger


class CompressionScheduler:
    def __init__(self, spec: CompressionSpec):
        self.spec = spec
        self._announced = set()

    def active(self, step: int) -> List[str]:
        out = []
        for i, t in enumerate(self.spec.techniques):
            if step >= t.schedule_offset:
                out.append(t.kind)
                if i not in self._announced:
                    self._announced.add(i)
                    logger.info(f"compression activated at step {step}: "
                                f"{t.kind} modules={t.modules}")
        return out

    def status(self, step: int) -> Dict[str, Dict]:
        st = {}
        for t in self.spec.techniques:
            entry = {"active": step >= t.schedule_offset}
            if t.kind == "weight_quantization":
                entry["bits"] = _current_bits(t, step)
            st.setdefault(t.kind, entry)
        return st
