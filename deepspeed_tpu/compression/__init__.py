"""Model compression (analog of ``deepspeed/compression/``)."""
from deepspeed_tpu.compression.compress import (apply_compression, student_initialization,
                                                init_compression,
                                                redundancy_clean,
                                                seed_masks)
from deepspeed_tpu.compression.scheduler import CompressionScheduler

__all__ = ["init_compression", "apply_compression", "redundancy_clean", "student_initialization",
           "seed_masks", "CompressionScheduler"]
