"""Config-driven compression over functional param trees.

Analog of ``deepspeed/compression/compress.py`` (``init_compression``
``:97``, ``redundancy_clean`` ``:127``) and the compressed-module zoo
(``basic_layer.py:61-887``). The reference swaps nn.Modules for
``LinearLayer_Compress``; with functional params the same techniques are
*tree transforms* applied inside the train step:

* weight quantization — groupwise fake-quant (QAT), bit-width annealed
  from ``start_bits`` to ``target_bits`` every ``quantization_period``
  steps after ``schedule_offset``
* sparse pruning — l1/topk magnitude masks at ``dense_ratio``
* row pruning — structured row masks on matched matrices
* head pruning — attention-head masks on [E, H, D]-shaped projections

Config keys mirror the reference (``shared_parameters`` /
``different_groups`` with ``modules`` glob-ish matching on param paths).
``redundancy_clean`` physically drops pruned rows/heads after training.
"""
from __future__ import annotations

import dataclasses
import fnmatch
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.quantizer import fake_quantize


@dataclasses.dataclass
class TechniqueSpec:
    kind: str                    # weight_quantization | sparse_pruning | ...
    schedule_offset: int
    params: Dict[str, Any]
    modules: List[str]

    def matches(self, path: str) -> bool:
        return any(m == "*" or fnmatch.fnmatch(path, f"*{m}*")
                   for m in self.modules)


@dataclasses.dataclass
class CompressionSpec:
    techniques: List[TechniqueSpec]
    masks: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)

    def for_path(self, path: str) -> List[TechniqueSpec]:
        return [t for t in self.techniques if t.matches(path)]


_KINDS = ("weight_quantization", "sparse_pruning", "row_pruning",
          "head_pruning", "channel_pruning", "activation_quantization")


def _flatten(tree) -> Dict[str, Any]:
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out["/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                     for p in path)] = leaf
    return out


def init_compression(params, compression_config: Dict) -> CompressionSpec:
    """Parse the ``compression_training`` config section into a spec bound
    to the param tree (validates that each group matches something)."""
    cfg = compression_config.get("compression_training",
                                 compression_config)
    techniques: List[TechniqueSpec] = []
    for kind in _KINDS:
        section = cfg.get(kind)
        if not section:
            continue
        shared = section.get("shared_parameters", {})
        if not shared.get("enabled", False):
            continue
        offset = shared.get("schedule_offset", 0)
        for gname, group in section.get("different_groups", {}).items():
            techniques.append(TechniqueSpec(
                kind=kind, schedule_offset=offset,
                params={**shared, **group.get("params", {})},
                modules=group.get("modules", ["*"])))
    spec = CompressionSpec(techniques=techniques)
    flat = _flatten(params)
    for t in spec.techniques:
        if not any(t.matches(p) for p in flat):
            raise ValueError(
                f"compression group for {t.kind} matches no parameter "
                f"(modules={t.modules})")
    return spec


def _current_bits(t: TechniqueSpec, step: int) -> int:
    start = int(t.params.get("start_bits", 8))
    target = int(t.params.get("target_bits", 8))
    period = int(t.params.get("quantization_period", 1) or 1)
    active = max(0, step - t.schedule_offset)
    drops = active // period
    return max(target, start - drops)


def apply_compression(params, spec: CompressionSpec, step: int):
    """Return the compressed view of ``params`` for this step — apply
    inside the forward/loss so QAT gradients flow (straight-through via
    fake-quant) and masks stay applied."""
    flat = _flatten(params)
    new_flat = dict(flat)
    for path, w in flat.items():
        if not hasattr(w, "ndim") or w.ndim < 2:
            continue
        for t in spec.for_path(path):
            if step < t.schedule_offset:
                continue
            if t.kind == "weight_quantization":
                bits = _current_bits(t, step)
                groups = int(t.params.get("quantize_groups", 1))
                sym = t.params.get("quantization_type",
                                   "symmetric") == "symmetric"
                w2 = w.reshape(-1, w.shape[-1])
                g = max(1, min(groups, w2.shape[0]))
                while w2.shape[0] % g:
                    g -= 1
                w = fake_quantize(w2, groups=g, bits=bits,
                                  symmetric=sym).reshape(w.shape)
            elif t.kind in ("sparse_pruning", "row_pruning",
                            "channel_pruning", "head_pruning"):
                mask = _get_mask(spec, path, t, w)
                w = w * mask.astype(w.dtype)
        new_flat[path] = w
    treedef = jax.tree_util.tree_structure(params)
    order = list(_flatten(params))
    return jax.tree_util.tree_unflatten(
        treedef, [new_flat[k] for k in order])


def seed_masks(params, spec: CompressionSpec, step: int) -> None:
    """Eagerly compute all pruning masks from the current (concrete)
    weights. Call once before jitting a train step that applies
    compression — masks are data-dependent and cannot be derived inside a
    trace (the reference likewise snapshots masks on module init)."""
    flat = _flatten(params)
    for path, w in flat.items():
        if not hasattr(w, "ndim") or w.ndim < 2:
            continue
        for t in spec.for_path(path):
            if step < t.schedule_offset or t.kind == "weight_quantization":
                continue
            _get_mask(spec, path, t, w)


def _get_mask(spec: CompressionSpec, path: str, t: TechniqueSpec, w):
    key = f"{t.kind}::{path}"
    if key in spec.masks:
        return jnp.asarray(spec.masks[key])
    if isinstance(w, jax.core.Tracer):
        raise ValueError(
            f"pruning mask for {path} requested inside a jit/grad trace "
            "before it was computed — call seed_masks(params, spec, step) "
            "eagerly first (masks are derived from concrete weights)")
    ratio = float(t.params.get("dense_ratio", 0.5))
    wnp = np.asarray(jax.device_get(w), np.float32)
    if t.kind == "sparse_pruning":
        method = t.params.get("method", "l1")
        flat = np.abs(wnp).reshape(-1)
        k = max(1, int(len(flat) * ratio))
        if method in ("l1", "topk"):
            thresh = np.partition(flat, -k)[-k]
            mask = (np.abs(wnp) >= thresh).astype(np.float32)
        else:
            raise ValueError(f"unknown sparse method {method}")
    elif t.kind in ("row_pruning", "channel_pruning"):
        axis = 0 if t.kind == "row_pruning" else -1
        scores = np.abs(wnp).sum(axis=tuple(
            a for a in range(wnp.ndim) if a != (axis % wnp.ndim)))
        k = max(1, int(len(scores) * ratio))
        keep = np.argsort(scores)[-k:]
        mask = np.zeros_like(scores)
        mask[keep] = 1.0
        shape = [1] * wnp.ndim
        shape[axis % wnp.ndim] = len(scores)
        mask = mask.reshape(shape)
    elif t.kind == "head_pruning":
        if wnp.ndim != 3:
            return jnp.ones_like(jnp.asarray(wnp))
        num_heads = wnp.shape[1]
        keep_n = max(1, int(num_heads * ratio))
        scores = np.abs(wnp).sum(axis=(0, 2))
        keep = np.argsort(scores)[-keep_n:]
        mask = np.zeros((1, num_heads, 1), np.float32)
        mask[0, keep, 0] = 1.0
    else:
        raise ValueError(t.kind)
    spec.masks[key] = mask
    return jnp.asarray(mask)


def redundancy_clean(params, spec: CompressionSpec):
    """Physically remove rows/heads that are fully masked (reference
    ``redundancy_clean`` compress.py:127). Returns (clean_params, report).
    Only leaves whose masks zero entire slices shrink; quantized weights
    are left fake-quantized (storage quantization is the serving writer's
    job)."""
    flat = _flatten(params)
    report = {}
    new_flat = dict(flat)
    for key, mask in spec.masks.items():
        kind, path = key.split("::", 1)
        if path not in flat or kind not in ("row_pruning", "head_pruning",
                                            "channel_pruning"):
            continue
        w = np.asarray(jax.device_get(flat[path]))
        m = np.asarray(mask)
        axis = int(np.argmax([s > 1 for s in m.shape]))
        keep = np.nonzero(m.reshape(-1) > 0)[0]
        neww = np.take(w, keep, axis=axis)
        new_flat[path] = jnp.asarray(neww)
        report[path] = {"kind": kind, "axis": axis,
                        "kept": int(len(keep)),
                        "of": int(m.reshape(-1).shape[0])}
    treedef = jax.tree_util.tree_structure(params)
    order = list(flat)
    return jax.tree_util.tree_unflatten(
        treedef, [new_flat[k] for k in order]), report


def student_initialization(student_params, teacher_params,
                           compression_config: Dict):
    """Layer-reduction knowledge-distillation init (reference
    ``compress.py:182`` ``student_initialization``): seed a shallow
    student from selected teacher layers before distillation.

    ``compression_config["layer_reduction"]``:
      module_name_prefix: path of the layer container in the param tree
          (e.g. ``"layers"`` for the fused inference tree, ``"blocks"``
          for GPT2LMModel's stacked tree)
      teacher_layer: teacher layer index per student layer, in order
      other_module_name: additional top-level subtrees copied verbatim
          (embeddings, final LN, lm head)

    Functional: returns a NEW student tree; handles both list-of-layers
    containers and stacked arrays with a leading layer dim.
    """
    cfg = compression_config
    if "compression_training" in cfg:      # full ds-config form
        cfg = cfg["compression_training"]
    cfg = cfg.get("layer_reduction", cfg)
    if not cfg or cfg.get("enabled") is False:
        return student_params
    if "module_name_prefix" not in cfg or "teacher_layer" not in cfg:
        raise ValueError(
            "layer_reduction config needs module_name_prefix and "
            "teacher_layer (reference compress.py:182)")
    prefix = cfg["module_name_prefix"]
    teacher_layer = list(cfg["teacher_layer"])
    other = list(cfg.get("other_module_name", []))

    def get_path(tree, path):
        node = tree
        for part in path.split("."):
            node = node[int(part)] if part.isdigit() else node[part]
        return node

    def set_path(tree, path, value):
        parts = path.split(".")
        node = tree
        for part in parts[:-1]:
            node = node[int(part)] if part.isdigit() else node[part]
        last = parts[-1]
        node[int(last) if last.isdigit() else last] = value

    out = jax.tree_util.tree_map(lambda x: x, student_params)  # deep-ish copy
    s_container = get_path(out, prefix)
    t_container = get_path(teacher_params, prefix)

    if isinstance(s_container, list):
        if len(teacher_layer) != len(s_container):
            raise ValueError(
                f"teacher_layer maps {len(teacher_layer)} layers but the "
                f"student has {len(s_container)}")
        for s_idx, t_idx in enumerate(teacher_layer):
            s_container[s_idx] = jax.tree_util.tree_map(
                lambda x: x, t_container[t_idx])
    else:
        # stacked arrays: leading dim = layer
        n_student = jax.tree_util.tree_leaves(s_container)[0].shape[0]
        if len(teacher_layer) != n_student:
            raise ValueError(
                f"teacher_layer maps {len(teacher_layer)} layers but the "
                f"student has {n_student}")
        idx = jnp.asarray(teacher_layer, jnp.int32)
        set_path(out, prefix, jax.tree_util.tree_map(
            lambda t: jnp.take(t, idx, axis=0), t_container))
    for name in other:
        set_path(out, name, get_path(teacher_params, name))
    return out
