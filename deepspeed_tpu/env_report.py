"""``dstpu_report`` — environment & op compatibility report.

Analog of ``deepspeed/env_report.py`` (``ds_report`` CLI, 143 LoC): prints
the framework/runtime version matrix and an op-availability table. On TPU
"op installed" means the Pallas kernel imports and traces (no JIT C++
builds), plus the native host-side ops (C++ CPU-Adam / AIO) when built.
"""
from __future__ import annotations

import importlib
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


OPS = {
    "flash_attention": "deepspeed_tpu.ops.pallas.flash_attention",
    "decode_attention": "deepspeed_tpu.ops.pallas.decode_attention",
    "fused_layer_norm": "deepspeed_tpu.ops.pallas.layer_norm",
    "quantizer": "deepspeed_tpu.ops.quantizer",
    "random_ltd": "deepspeed_tpu.ops.random_ltd",
    "ring_attention": "deepspeed_tpu.ops.ring_attention",
    "optimizers": "deepspeed_tpu.ops.adam",
}


def op_report():
    rows = []
    for name, mod in sorted(OPS.items()):
        try:
            importlib.import_module(mod)
            rows.append((name, True, ""))
        except Exception as e:  # pragma: no cover - env specific
            rows.append((name, False, str(e)[:60]))
    return rows


def versions():
    out = {}
    import deepspeed_tpu
    out["deepspeed_tpu"] = deepspeed_tpu.__version__
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint",
                "numpy"):
        try:
            m = importlib.import_module(mod)
            out[mod] = getattr(m, "__version__", "?")
        except Exception:
            out[mod] = "not installed"
    return out


def device_info():
    try:
        import jax
        devs = jax.devices()
        return {"backend": jax.default_backend(),
                "device_count": len(devs),
                "devices": [str(d) for d in devs[:8]]}
    except Exception as e:  # pragma: no cover
        return {"backend": f"unavailable: {e}", "device_count": 0,
                "devices": []}


def telemetry_info():
    """Telemetry/flight-recorder status: registry + event ring state,
    which config-gated surfaces the defaults arm, and per-device HBM
    totals when the backend reports them (docs/observability.md)."""
    out = {}
    try:
        from deepspeed_tpu.telemetry import (TelemetryConfig,
                                             get_event_ring, get_registry)
        cfg = TelemetryConfig()
        reg = get_registry()
        ring = get_event_ring()
        out["telemetry"] = ("on (registry default; "
                            f"{len(reg.snapshot())} metric families)"
                            if cfg.enabled else "off")
        out["scrape_endpoint"] = (
            f"port {cfg.http_port}" if cfg.http_port is not None
            else "off (set telemetry.http_port)")
        out["event_ring"] = f"{len(ring)}/{ring.capacity} events"
        out["hang_watchdog"] = (
            f"{cfg.watchdog_deadline_s}s deadline"
            if cfg.watchdog_deadline_s is not None
            else "off (set telemetry.watchdog_deadline_s)")
        from deepspeed_tpu.telemetry import numerics_snapshot
        watches = numerics_snapshot()
        # registration is the /debug/numerics reporting hook and happens
        # even with numerics off — report the enabled state separately
        state = ("enabled by default config" if cfg.numerics_enabled
                 else "off (set telemetry.numerics_enabled)")
        if watches:
            state += (f"; {len(watches)} watch(es) registered: "
                      f"{sorted(watches)}")
        out["numerics_watch"] = state
        out["goodput"] = ("on by default config" if cfg.goodput
                          else "off (set telemetry.goodput)")
        out["step_profile"] = (
            "on by default config (serve step phase decomposition + "
            "goodput fraction + dispatch-gap detector; /debug/goodput; "
            f"ring/timeline sample every {cfg.step_profile_events_every}"
            " steps)"
            if cfg.step_profile
            else "off (set telemetry.step_profile)")
        out["kv_pool_accounting"] = (
            "on by default config (block lifetime / age-at-eviction "
            "histograms, free-list fragmentation gauge, per-request "
            "peak blocks, famine ring snapshots)"
            if cfg.step_profile
            else "off (rides telemetry.step_profile)")
        out["request_tracing"] = (
            f"sample rate {cfg.trace_sample_rate}, ring "
            f"{cfg.trace_ring_capacity}, slow-keep "
            f"{cfg.trace_slow_threshold_s}s"
            if cfg.trace_sample_rate > 0
            else "off (set telemetry.trace_sample_rate)")
        slo_targets = [k for k in ("ttft_p90_s", "token_p50_s",
                                   "queue_wait_p90_s", "error_rate")
                       if getattr(cfg.slo, k) is not None]
        out["slo_gates"] = (
            f"on ({len(slo_targets)} objective(s): "
            f"{', '.join(slo_targets)}; window {cfg.slo.window_s}s)"
            if cfg.slo.enabled and slo_targets
            else "off (set telemetry.slo.enabled + objectives)")
        # the SLO closed loop (docs/observability.md "SLOs, alerting &
        # incidents"): declared burn-rate rules + canary/incident arm
        # state from the default config, live firing count from the
        # process registry, and the newest bundle path any recorder in
        # this process wrote
        from deepspeed_tpu.telemetry import last_incident_path
        rules = sorted(cfg.slo.objectives)
        firing = 0
        fam = reg.snapshot().get("serve_alert_firing")
        if fam:
            firing = sum(1 for s in fam["series"] if s["value"] >= 1.0)
        parts = [
            (f"{len(rules)} alert rule(s): {', '.join(rules)}"
             if cfg.slo.enabled and rules else
             "no alert rules (set telemetry.slo.enabled + "
             "slo.objectives)"),
            (f"canary every {cfg.canary.interval_s}s"
             if cfg.canary.enabled else
             "canary off (set telemetry.canary.enabled)"),
            (f"incident bundles -> {cfg.incident.dir or 'in-memory'}"
             if cfg.incident.enabled else
             "incident bundles off (set telemetry.incident.enabled)"),
            f"{firing} rule(s) firing now",
        ]
        last = last_incident_path()
        if last:
            parts.append(f"last incident {last}")
        out["serve_slo"] = "; ".join(parts)
        from deepspeed_tpu.inference.config import \
            DeepSpeedInferenceConfig
        icfg = DeepSpeedInferenceConfig()
        k = icfg.speculation_tokens
        out["serve_speculation"] = (
            f"on by default config (speculation_tokens={k}, "
            "prompt-lookup or draft-model proposals "
            "(speculation_draft), batched paged verify)"
            if k else
            "off (set DeepSpeedInferenceConfig.speculation_tokens>=2 — "
            "docs/serving.md 'Per-slot speculative decoding')")
        if icfg.async_loop:
            # configured vs OBSERVED lag: the step profiler's
            # serve_commit_lag_depth histogram records the chain depth
            # at every dispatch in this process — report its deepest
            # bucket beside the config knob when any server has run
            blurb = (f"on by default config (pipelined dispatch, "
                     f"lag-{icfg.max_commit_lag} host commit "
                     f"(max_commit_lag), worker-thread publish, flush "
                     f"on host actions — docs/serving.md 'Async "
                     f"dispatch loop')")
            fam = reg.snapshot().get("serve_commit_lag_depth")
            if fam:
                # buckets are [upper_bound, count] pairs; the deepest
                # non-empty finite bucket's bound IS the observed depth
                # (integer-valued observations on integer bounds)
                depths = [b for s in fam["series"]
                          for b, n in s.get("buckets", [])
                          if n and b != float("inf")]
                if depths:
                    blurb += (f"; observed chain depth up to "
                              f"{max(depths):g} this process")
            out["serve_async_loop"] = blurb
        else:
            out["serve_async_loop"] = (
                "off (set DeepSpeedInferenceConfig.async_loop=true)")
        out["serve_kv_dtype"] = (
            "int8 by default config (per-block-per-head scales, VMEM "
            "dequant in the paged kernels)"
            if icfg.kv_cache_dtype == "int8" else
            "fp by default config (set kv_cache_dtype='int8' for ~2x "
            "KV capacity — docs/serving.md 'KV quantization & host "
            "tiering')")
        out["serve_kv_host_offload"] = (
            f"on by default config (cold prefix blocks demote to host "
            f"RAM, cap {icfg.kv_host_blocks or 'unbounded'} blocks)"
            if icfg.kv_host_offload else
            "off (set kv_host_offload=true + enable_prefix_caching — "
            "demotion replaces eviction, swap-in restores on prefix "
            "hits)")
        rc = icfg.replication
        out["serve_replication"] = (
            f"{rc.replicas} replicas by default config (health-checked "
            f"routing, failover after {rc.heartbeat_dead_s}s heartbeat "
            f"silence, {rc.max_failovers} retries)"
            if rc.replicas > 1 else
            "single replica (set replication.replicas > 1 for the "
            "supervised pool — health-checked routing, mid-flight "
            "failover, rolling drain; docs/serving.md 'Replicated "
            "serving & failover')")
        out["serve_disaggregation"] = (
            f"role topology {rc.roles} by default config (chain-hash "
            f"KV handoff, telemetry-routed decode admission, handoff "
            f"tier cap {rc.handoff_blocks or 'unbounded'} blocks)"
            if rc.disaggregated else
            "colocated (set replication.roles, e.g. "
            "['prefill','decode'] — prefill replicas chunk-prefill "
            "only and hand KV off by chain hash to telemetry-picked "
            "decode replicas; docs/serving.md 'Disaggregated "
            "prefill/decode')")
        out["serve_fleet_obs"] = (
            f"{rc.replicas} replicas federated into one /metrics "
            f"scrape (replica-labeled merge, staleness-marked "
            f"snapshots), trace stitching "
            f"{'on' if cfg.trace_sample_rate > 0 else 'off'} "
            f"(sample rate {cfg.trace_sample_rate})"
            if rc.replicas > 1 else
            "single replica — fleet plane idle (with "
            "replication.replicas > 1 the frontend merges every "
            "replica's instruments under replica labels, stitches "
            "cross-replica request legs into one trace, and serves "
            "/debug/fleet + a merged timeline; docs/observability.md "
            "'Fleet observability')")
        out["serve_accounting"] = (
            f"on by default config (per-request device-second ledger "
            f"closing against the step profiler, KV block-seconds, "
            f"tenant metering top-{cfg.accounting.max_tenants}, live "
            f"capacity model window {cfg.accounting.window_s}s at "
            f"/debug/capacity)"
            if cfg.accounting.enabled and cfg.step_profile else
            "off (needs telemetry.step_profile + "
            "telemetry.accounting.enabled — docs/observability.md "
            "'Cost accounting & capacity')")
        fic = cfg.fault_injection
        out["fault_injection"] = (
            f"ARMED (seed {fic.seed}; step latency "
            f"{fic.step_latency_s}s, prefill failure rate "
            f"{fic.prefill_failure_rate}, famine {fic.famine_blocks} "
            f"blocks, wedge every {fic.wedge_nth_request})"
            if fic.enabled
            else "off (chaos hooks; telemetry.fault_injection — "
                 "training kinds: step_crash / nan_burst / data_stall / "
                 "preempt_step / ckpt_write_failure / ckpt_corrupt)")
        from deepspeed_tpu.config.config import (CheckpointConfig,
                                                 ResilienceConfig)
        ckpt = CheckpointConfig()
        out["ckpt_integrity"] = (
            f"verified atomic commit by default config (per-file sha256 "
            f"manifest, 'latest' advances post-verify, load fallback "
            f"ladder; retention keep_last="
            f"{ckpt.keep_last or 'unbounded'})"
            if ckpt.verify else
            "off (set checkpoint.verify — docs/training.md "
            "'Fault-tolerant training & verified checkpoints')")
        res = ResilienceConfig()
        from deepspeed_tpu.runtime.resilience import resilience_snapshot
        live = resilience_snapshot()
        state = (
            f"defaults: checkpoint every {res.checkpoint_every} steps, "
            f"{res.max_restarts} restarts, backoff "
            f"{res.backoff_base_s}-{res.backoff_max_s}s "
            "(wrap the loop with runtime/resilience.py "
            "TrainingSupervisor; GET /debug/resilience)")
        if live.get("enabled"):
            sups = live["supervisors"]
            state = (f"{len(sups)} supervisor(s) live: " + "; ".join(
                f"{s.get('status')} step {s.get('step')} "
                f"restarts {s.get('restarts')}" for s in sups))
        out["train_resilience"] = state
    except Exception as e:  # pragma: no cover - env specific
        out["telemetry"] = f"unavailable: {e}"
        return out
    try:
        import jax
        hbm = []
        for d in jax.local_devices():
            stats = dict(d.memory_stats() or {})
            limit = int(stats.get("bytes_limit", 0))
            used = int(stats.get("bytes_in_use", 0))
            if limit:
                hbm.append(f"{d.id}: {used / 2**30:.2f}/"
                           f"{limit / 2**30:.2f} GiB")
        out["device_hbm"] = "; ".join(hbm) if hbm \
            else "no allocator stats (CPU backend?)"
    except Exception:  # pragma: no cover - env specific
        out["device_hbm"] = "unavailable"
    return out


def main(hide_operator_status=False, hide_errors_and_warnings=False):
    print("-" * 64)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 64)
    if not hide_operator_status:
        print(f"{'op name':<24}{'status':<12}")
        print("-" * 64)
        for name, ok, err in op_report():
            status = GREEN_OK if ok else RED_NO
            line = f"{name:<24}{status:<12}"
            if err and not hide_errors_and_warnings:
                line += f"  {err}"
            print(line)
    print("-" * 64)
    print("DeepSpeed-TPU general environment info:")
    for k, v in versions().items():
        print(f"{k:<24}{v}")
    for k, v in device_info().items():
        print(f"{k:<24}{v}")
    print("-" * 64)
    print("DeepSpeed-TPU telemetry / flight recorder:")
    for k, v in telemetry_info().items():
        print(f"{k:<24}{v}")
    print("-" * 64)
    return 0


def cli_main():  # console entry
    sys.exit(main())


if __name__ == "__main__":
    cli_main()
