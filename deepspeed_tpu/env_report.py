"""``dstpu_report`` — environment & op compatibility report.

Analog of ``deepspeed/env_report.py`` (``ds_report`` CLI, 143 LoC): prints
the framework/runtime version matrix and an op-availability table. On TPU
"op installed" means the Pallas kernel imports and traces (no JIT C++
builds), plus the native host-side ops (C++ CPU-Adam / AIO) when built.
"""
from __future__ import annotations

import importlib
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


OPS = {
    "flash_attention": "deepspeed_tpu.ops.pallas.flash_attention",
    "decode_attention": "deepspeed_tpu.ops.pallas.decode_attention",
    "fused_layer_norm": "deepspeed_tpu.ops.pallas.layer_norm",
    "quantizer": "deepspeed_tpu.ops.quantizer",
    "random_ltd": "deepspeed_tpu.ops.random_ltd",
    "ring_attention": "deepspeed_tpu.ops.ring_attention",
    "optimizers": "deepspeed_tpu.ops.adam",
}


def op_report():
    rows = []
    for name, mod in sorted(OPS.items()):
        try:
            importlib.import_module(mod)
            rows.append((name, True, ""))
        except Exception as e:  # pragma: no cover - env specific
            rows.append((name, False, str(e)[:60]))
    return rows


def versions():
    out = {}
    import deepspeed_tpu
    out["deepspeed_tpu"] = deepspeed_tpu.__version__
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint",
                "numpy"):
        try:
            m = importlib.import_module(mod)
            out[mod] = getattr(m, "__version__", "?")
        except Exception:
            out[mod] = "not installed"
    return out


def device_info():
    try:
        import jax
        devs = jax.devices()
        return {"backend": jax.default_backend(),
                "device_count": len(devs),
                "devices": [str(d) for d in devs[:8]]}
    except Exception as e:  # pragma: no cover
        return {"backend": f"unavailable: {e}", "device_count": 0,
                "devices": []}


def main(hide_operator_status=False, hide_errors_and_warnings=False):
    print("-" * 64)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 64)
    if not hide_operator_status:
        print(f"{'op name':<24}{'status':<12}")
        print("-" * 64)
        for name, ok, err in op_report():
            status = GREEN_OK if ok else RED_NO
            line = f"{name:<24}{status:<12}"
            if err and not hide_errors_and_warnings:
                line += f"  {err}"
            print(line)
    print("-" * 64)
    print("DeepSpeed-TPU general environment info:")
    for k, v in versions().items():
        print(f"{k:<24}{v}")
    for k, v in device_info().items():
        print(f"{k:<24}{v}")
    print("-" * 64)
    return 0


def cli_main():  # console entry
    sys.exit(main())


if __name__ == "__main__":
    cli_main()
