"""Top-k gated Mixture-of-Experts with expert parallelism.

TPU-native analog of ``deepspeed/moe/sharded_moe.py``: the gating math
(top-1 :177 and top-2 :278 with capacity, noisy gating, Random Token
Selection) ports as pure jnp; the dispatch/combine einsums follow the same
GShard dimension grammar (g=group, s=sequence, e=expert, c=capacity,
m=model). The explicit ``_AllToAll`` autograd function (:89) disappears:
dispatched tokens are sharding-constrained from the group(data) axis to the
expert axis, and XLA's SPMD partitioner emits the all-to-all (and its
transpose in backward) over ICI.

Capacity is STATIC under jit: computed from static shapes exactly like the
reference's ``_capacity`` (:155). ``drop_tokens=False`` maps to capacity =
group size (the no-drop worst case) instead of the reference's dynamic
max-count allreduce (:214) — dynamic shapes would force retracing.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# expert parallelism folds over the ZeRO/data axes (reference reuses DP ranks
# for expert groups — deepspeed/utils/groups.py:109)
EP_AXES = ("data", "fsdp")


from deepspeed_tpu.utils.sharding import maybe_constrain as _constrain


def capacity(num_tokens: int, num_experts: int, capacity_factor: float,
             min_capacity: int) -> int:
    """Static per-expert capacity (reference _capacity, sharded_moe.py:155)."""
    cap = math.ceil((num_tokens / num_experts) * capacity_factor)
    return max(cap, min_capacity)


def _gumbel(rng, shape):
    return -jnp.log(-jnp.log(
        jax.random.uniform(rng, shape, jnp.float32, 1e-20, 1.0 - 1e-10)
    ) + 1e-20)


_warned_missing_rng: set = set()


def warn_missing_training_rng(what: str) -> None:
    """A TRAINING-mode gate without an rng silently loses exploration
    noise (gumbel 2nd expert, RTS). Called from TopKGate — the layer that
    knows train intent; rng=None at eval is the CORRECT deterministic
    routing and must stay silent. Once per process; trace-time only."""
    if what in _warned_missing_rng:
        return
    _warned_missing_rng.add(what)
    from deepspeed_tpu.utils.logging import logger
    logger.warning(
        "%s: train=True but no gating rng — routing deterministically "
        "(no gumbel/RTS noise). Pass rng or provide a 'gating' PRNG "
        "stream for training-time gate exploration.", what)


def _keep_topk_tokens(mask: jax.Array, score: jax.Array, k: int) -> jax.Array:
    """Per (group, expert), keep only the k highest-scoring tokens of
    ``mask`` (Random Token Selection uses random scores — reference :225).

    mask, score: [G, S, E]; returns mask with at most k ones per (g, e).
    """
    S = mask.shape[1]
    k = min(k, S)
    scored = jnp.where(mask > 0, score, -jnp.inf)  # [G, S, E]
    _, idx = jax.lax.top_k(jnp.swapaxes(scored, 1, 2), k)  # [G, E, k]
    keep = jax.nn.one_hot(idx, S, dtype=mask.dtype).sum(axis=2)  # [G, E, S]
    return mask * jnp.swapaxes(keep, 1, 2)


def top1_gating(logits: jax.Array,
                capacity_factor: float = 1.0,
                min_capacity: int = 4,
                rng: Optional[jax.Array] = None,
                noisy_gate_policy: Optional[str] = None,
                drop_tokens: bool = True,
                use_rts: bool = True,
                used_token: Optional[jax.Array] = None
                ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Top-1 gating (reference top1gating, sharded_moe.py:177).

    logits: [G, S, E] fp32. Returns (l_aux, combine_weights [G,S,E,C],
    dispatch_mask [G,S,E,C] bool, exp_counts [E]).
    """
    G, S, E = logits.shape
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)

    C = capacity(S, E, capacity_factor, min_capacity)
    if not drop_tokens:
        C = S
    C = min(C, S)

    if noisy_gate_policy == "RSample":
        if rng is None:
            raise ValueError("RSample noisy gating needs an rng")
        rng, sub = jax.random.split(rng)
        select_from = logits + _gumbel(sub, logits.shape)
    else:
        select_from = gates
    indices1 = jnp.argmax(select_from, axis=-1)  # [G, S]
    mask1 = jax.nn.one_hot(indices1, E, dtype=jnp.int32)  # [G, S, E]
    if used_token is not None:
        mask1 = mask1 * used_token[..., None].astype(jnp.int32)

    exp_counts = mask1.sum(axis=(0, 1))  # [E]

    # load-balancing loss (reference :220-222)
    me = jnp.mean(gates, axis=(0, 1))
    ce = jnp.mean(mask1.astype(jnp.float32), axis=(0, 1))
    l_aux = jnp.sum(me * ce) * E

    # Random Token Selection: keep a random C-subset instead of the first C
    # (reference :224-243); deterministic first-come order when disabled —
    # and also when rng is None (eval routing must be deterministic; the
    # reference applies RTS in training only).
    if use_rts and rng is not None:
        score = jax.random.uniform(rng, mask1.shape, jnp.float32)
    else:
        # prefer earlier tokens, mirroring pure cumsum-order dropping
        score = -jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.float32)[None, :, None], mask1.shape)
    mask1 = _keep_topk_tokens(mask1, score, C)

    locations1 = jnp.cumsum(mask1, axis=1) - 1  # [G, S, E]
    locations1_s = jnp.sum(locations1 * mask1, axis=-1)  # [G, S]

    gates = gates * mask1.astype(jnp.float32)
    locations1_sc = jax.nn.one_hot(locations1_s, C, dtype=jnp.float32)
    combine_weights = jnp.einsum("gse,gsc->gsec", gates, locations1_sc)
    dispatch_mask = combine_weights > 0
    return l_aux, combine_weights, dispatch_mask, exp_counts


def top2_gating(logits: jax.Array,
                capacity_factor: float = 1.0,
                min_capacity: int = 4,
                rng: Optional[jax.Array] = None
                ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Top-2 gating (reference top2gating, sharded_moe.py:278)."""
    G, S, E = logits.shape
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)

    C = capacity(S, E, capacity_factor * 2.0, min_capacity)
    C = min(C, S)

    indices1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(indices1, E, dtype=jnp.int32)

    # second expert via the Gumbel-max trick (reference :297-303).
    # rng=None → deterministic exact-2nd-argmax: eval/serving routing
    # must not be noisy (the reference's moe_inference uses exact top-k);
    # TopKGate warns when a TRAINING call arrives without an rng
    logits_w_noise = (logits if rng is None
                      else logits + _gumbel(rng, logits.shape))
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits_w_noise)
    indices2 = jnp.argmax(logits_except1, axis=-1)
    mask2 = jax.nn.one_hot(indices2, E, dtype=jnp.int32)

    locations1 = jnp.cumsum(mask1, axis=1) - 1
    locations2 = jnp.cumsum(mask2, axis=1) - 1
    # 2nd-choice tokens queue behind all 1st choices (reference :309)
    locations2 = locations2 + jnp.sum(mask1, axis=1, keepdims=True)

    exp_counts = mask1.sum(axis=(0, 1))

    me = jnp.mean(gates, axis=(0, 1))
    ce = jnp.mean(mask1.astype(jnp.float32), axis=(0, 1))
    l_aux = jnp.mean(me * ce) * E * E

    mask1 = mask1 * (locations1 < C).astype(jnp.int32)
    mask2 = mask2 * (locations2 < C).astype(jnp.int32)

    locations1_s = jnp.sum(locations1 * mask1, axis=-1)
    locations2_s = jnp.sum(locations2 * mask2, axis=-1)

    mask1f = mask1.astype(jnp.float32)
    mask2f = mask2.astype(jnp.float32)
    gates1_s = jnp.einsum("gse,gse->gs", gates, mask1f)
    gates2_s = jnp.einsum("gse,gse->gs", gates, mask2f)
    denom = jnp.clip(gates1_s + gates2_s, jnp.finfo(jnp.float32).eps, None)
    gates1_s = gates1_s / denom
    gates2_s = gates2_s / denom

    gates1 = jnp.einsum("gs,gse->gse", gates1_s, mask1f)
    gates2 = jnp.einsum("gs,gse->gse", gates2_s, mask2f)
    loc1_sc = jax.nn.one_hot(locations1_s, C, dtype=jnp.float32)
    loc2_sc = jax.nn.one_hot(locations2_s, C, dtype=jnp.float32)
    combine_weights = (jnp.einsum("gse,gsc->gsec", gates1, loc1_sc) +
                       jnp.einsum("gse,gsc->gsec", gates2, loc2_sc))
    dispatch_mask = combine_weights > 0
    return l_aux, combine_weights, dispatch_mask, exp_counts


def moe_dispatch_combine(expert_fn: Callable[[Any, jax.Array], jax.Array],
                         expert_params: Any,
                         x: jax.Array,
                         combine_weights: jax.Array,
                         dispatch_mask: jax.Array) -> jax.Array:
    """Dispatch → expert compute → combine (reference MOELayer.forward
    :491-523). ``x``: [G, S, M]; expert_fn maps [E, G*C, M] -> [E, G*C, M]
    with expert dim sharded over EP_AXES — the g→e reshard IS the reference's
    all-to-all (:89), emitted by XLA from the sharding constraints.
    """
    G, S, M = x.shape
    E, C = dispatch_mask.shape[2], dispatch_mask.shape[3]
    dispatched = jnp.einsum("gsec,gsm->egcm",
                            dispatch_mask.astype(x.dtype), x)
    dispatched = _constrain(dispatched, P(EP_AXES, None, None, None))
    out = expert_fn(expert_params, dispatched.reshape(E, G * C, M))
    out = out.reshape(E, G, C, M)
    out = _constrain(out, P(EP_AXES, None, None, None))
    y = jnp.einsum("gsec,egcm->gsm", combine_weights.astype(x.dtype), out)
    return _constrain(y, P(EP_AXES, None, None))
