"""MoE layer facade (flax) — analog of ``deepspeed/moe/layer.py``.

The reference's ``MoE`` module (layer.py:15) wires expert process groups,
a ``TopKGate`` and the all-to-all ``MOELayer``; here the facade is a flax
module whose expert parameters carry a leading expert dimension sharded over
the EP axes (see sharded_moe.EP_AXES) — the process-group plumbing
(``_create_expert_and_data_parallel_groups``, layer.py:90) reduces to
sharding specs, exposed via :meth:`tp_specs`.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.moe.sharded_moe import (EP_AXES, moe_dispatch_combine,
                                           top1_gating, top2_gating)


class Experts(nn.Module):
    """Stacked expert FFNs (reference moe/experts.py — a ModuleList there;
    one stacked einsum here so the MXU sees a single batched matmul).

    ``gated=True`` makes each expert a SwiGLU FFN (Mixtral-style:
    down(act(gate(x)) * up(x)), no biases) instead of the reference's
    two-matrix gelu FFN."""
    num_experts: int
    d_model: int
    d_hidden: int
    dtype: Any = jnp.bfloat16
    activation: Callable = nn.gelu
    gated: bool = False
    # SwitchBack int8 expert GEMMs (ops/int8_training.py batched twin):
    # fwd + dx on the int8 MXU, dw full precision
    int8_training: bool = False

    def _bmm(self, x, w):
        """[E, T, K] @ [E, K, N] expert matmul seam."""
        if self.int8_training:
            from deepspeed_tpu.ops.int8_training import (
                switchback_batched_matmul)
            return switchback_batched_matmul(x, w.astype(self.dtype))
        return jnp.einsum("etk,ekn->etn", x, w.astype(self.dtype))

    @nn.compact
    def __call__(self, x):  # x: [E, T, M]
        E, M, H = self.num_experts, self.d_model, self.d_hidden
        wi = self.param("wi", nn.initializers.normal(0.02), (E, M, H),
                        jnp.float32)
        wo = self.param("wo", nn.initializers.normal(0.02), (E, H, M),
                        jnp.float32)
        if self.gated:
            wg = self.param("wg", nn.initializers.normal(0.02), (E, M, H),
                            jnp.float32)
            g = self._bmm(x, wg)
            u = self._bmm(x, wi)
            h = self.activation(g) * u
            return self._bmm(h, wo)
        bi = self.param("bi", nn.initializers.zeros, (E, H), jnp.float32)
        bo = self.param("bo", nn.initializers.zeros, (E, M), jnp.float32)
        h = self._bmm(x, wi)
        h = self.activation(h + bi.astype(self.dtype)[:, None])
        y = self._bmm(h, wo)
        return y + bo.astype(self.dtype)[:, None]


def _gate_needs_rng(use_rts, k, noisy_gate_policy) -> bool:
    """True when training-time gating consumes randomness (RTS token
    selection, gumbel 2nd expert, or jitter noise)."""
    return bool(use_rts or k == 2 or noisy_gate_policy)


class TopKGate(nn.Module):
    """Gating head (reference sharded_moe.py:351 TopKGate): linear in fp32
    then top-1/top-2 gating."""
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True, rng=None):
        if self.k not in (1, 2):
            raise ValueError("Only top-1 and top-2 gatings are supported")
        if train and rng is None and _gate_needs_rng(
                self.use_rts, self.k, self.noisy_gate_policy):
            from deepspeed_tpu.moe.sharded_moe import \
                warn_missing_training_rng
            warn_missing_training_rng("TopKGate")
        # gate math runs in fp32 regardless of compute dtype (reference
        # TopKGate.forward casts input to fp32: sharded_moe.py:400)
        wg = self.param("wg", nn.initializers.normal(0.02),
                        (x.shape[-1], self.num_experts), jnp.float32)
        logits = jnp.einsum("gsm,me->gse", x.astype(jnp.float32), wg)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1_gating(
                logits, cf, self.min_capacity, rng=rng,
                noisy_gate_policy=self.noisy_gate_policy if train else None,
                drop_tokens=self.drop_tokens, use_rts=self.use_rts)
        return top2_gating(logits, cf, self.min_capacity, rng=rng)


class MoE(nn.Module):
    """Drop-in MoE block (reference deepspeed/moe/layer.py:15 ``MoE``).

    ``__call__(x)`` returns ``(output, l_aux, exp_counts)`` exactly like the
    reference's forward (layer.py:115).
    """
    hidden_size: int
    num_experts: int = 1
    ffn_hidden_size: Optional[int] = None
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    dtype: Any = jnp.bfloat16
    activation: Callable = nn.gelu
    gated_experts: bool = False    # Mixtral-style SwiGLU experts
    int8_training: bool = False    # SwitchBack expert GEMMs

    @nn.compact
    def __call__(self, x, train: bool = True, rng=None):
        squeeze = x.ndim == 2
        if squeeze:  # [T, M] -> single group
            x = x[None]
        # gate noise (rts, 2nd-expert gumbel, jitter) is a TRAINING
        # device; eval routing stays deterministic (rng=None) so serving
        # and train-time eval agree with the exact-top-k inference path
        if rng is None and train and _gate_needs_rng(
                self.use_rts, self.k, self.noisy_gate_policy):
            if self.has_rng("gating"):
                rng = self.make_rng("gating")
            else:
                # fixed-key fallback keeps training runnable, but every
                # step reuses the SAME noise — tell the user where the
                # missing 'gating' stream should come from
                from deepspeed_tpu.moe.sharded_moe import \
                    warn_missing_training_rng
                warn_missing_training_rng(
                    "MoE (no 'gating' PRNG stream; fixed-key noise)")
                rng = jax.random.PRNGKey(0)
        gate = TopKGate(self.num_experts, self.k, self.capacity_factor,
                        self.eval_capacity_factor, self.min_capacity,
                        self.noisy_gate_policy, self.drop_tokens,
                        self.use_rts, name="gate")
        l_aux, combine, dispatch, exp_counts = gate(x, train=train, rng=rng)
        experts = Experts(self.num_experts, self.hidden_size,
                          self.ffn_hidden_size or 4 * self.hidden_size,
                          dtype=self.dtype, activation=self.activation,
                          gated=self.gated_experts,
                          int8_training=self.int8_training,
                          name="experts")
        y = moe_dispatch_combine(
            lambda _, d: experts(d), None, x.astype(self.dtype),
            combine, dispatch)
        if squeeze:
            y = y[0]
        return y, l_aux, exp_counts

    @staticmethod
    def tp_specs(num_layers_prefix=(), gated: bool = False):
        """Sharding specs for the MoE params: experts sharded over the EP
        axes on their leading expert dim, gate replicated. ``gated`` must
        match the module's ``gated_experts`` (different param tree)."""
        experts = {"wi": P(EP_AXES, None, None),
                   "wo": P(EP_AXES, None, None)}
        if gated:
            experts["wg"] = P(EP_AXES, None, None)
        else:
            experts["bi"] = P(EP_AXES, None)
            experts["bo"] = P(EP_AXES, None)
        return {"gate": {"wg": P()}, "experts": experts}
