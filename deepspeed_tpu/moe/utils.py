"""MoE parameter utilities — analog of ``deepspeed/moe/utils.py``.

The reference splits torch param groups so ZeRO partitions expert params
over expert-data-parallel groups (``split_params_into_different_moe_groups_
for_optimizer``). Under sharding-by-construction the split is a pytree
predicate: expert leaves are the ones whose path passes through an
``experts`` collection, and their EP placement is carried by tp_specs.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax


def is_moe_param_path(path) -> bool:
    for k in path:
        key = getattr(k, "key", getattr(k, "name", None))
        if key is not None and "expert" in str(key):
            return True
    return False


def split_moe_params(params: Any) -> Tuple[Any, Any]:
    """Returns (dense_mask, expert_mask) boolean pytrees matching ``params``
    — usable for per-group optimizer settings (the reference's param-group
    split) or for counting."""
    dense = jax.tree_util.tree_map_with_path(
        lambda p, _: not is_moe_param_path(p), params)
    expert = jax.tree_util.tree_map_with_path(
        lambda p, _: is_moe_param_path(p), params)
    return dense, expert


def moe_param_count(params: Any) -> Tuple[int, int]:
    """(dense_count, expert_count) parameter totals."""
    dense = expert = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        if is_moe_param_path(path):
            expert += int(leaf.size)
        else:
            dense += int(leaf.size)
    return dense, expert
