"""Token gather/drop across the tensor-parallel axis.

Analog of ``deepspeed/moe/mappings.py`` (``gather_tokens``/``drop_tokens``
with ``_GatherTokens``/``_DropTokens`` autograd pairs, ``:27-110``): when
an MoE layer sits inside a TP region whose activations are
sequence-sharded across TP ranks, tokens must be gathered before expert
dispatch and re-dropped after, with the transposed collective as the
gradient.

Two execution contexts, same API:

* **GSPMD (default)** — axes are Auto: "gather" and "drop" are sharding
  constraints (replicated vs sharded along ``tensor``); XLA inserts the
  all-gather/slice and their transposes. This is the TPU-idiomatic form.
* **shard_map** — axes Manual: explicit ``lax.all_gather(tiled=True)``
  and the local slice. JAX differentiates both with the correct
  transpose pair, matching the reference's autograd functions.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.sharding import maybe_constrain

TENSOR_AXIS = "tensor"


def _axis_mode() -> str:
    """'manual' inside shard_map over tensor, 'auto' under GSPMD with a
    tensor axis, 'none' without a mesh."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty or TENSOR_AXIS not in mesh.axis_names:
        return "none"
    types = dict(zip(mesh.axis_names, mesh.axis_types))
    if types[TENSOR_AXIS] == jax.sharding.AxisType.Manual:
        return "manual"
    return "auto"


def _spec(x, dim: int, sharded: bool) -> P:
    # constrain ONLY the tensor placement on `dim`; every other dim stays
    # UNCONSTRAINED so shardings over other mesh axes (e.g. data on the
    # batch dim) survive the gather/drop
    entries: list = [P.UNCONSTRAINED] * x.ndim
    entries[dim] = TENSOR_AXIS if sharded else None
    return P(*entries)


def _local_slice(x: jax.Array, dim: int) -> jax.Array:
    n = jax.lax.axis_size(TENSOR_AXIS)
    if x.shape[dim] % n:
        raise ValueError(
            f"drop_tokens: dim {dim} size {x.shape[dim]} not "
            f"divisible by tensor={n} (reference asserts the same)")
    idx = jax.lax.axis_index(TENSOR_AXIS)
    chunk = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, dim)


# The autograd pairing matters: downstream TP compute is REPLICATED, so
# the backward of gather takes this rank's cotangent slice — NOT the
# psum-scatter jax's native all_gather transpose would insert (that
# convention is for sharded-sum losses and over-counts by tp_size here).
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _gather_manual(x, dim):
    return jax.lax.all_gather(x, TENSOR_AXIS, axis=dim, tiled=True)


def _gather_fwd(x, dim):
    return _gather_manual(x, dim), None


def _gather_bwd(dim, _, ct):
    return (_local_slice(ct, dim),)


_gather_manual.defvjp(_gather_fwd, _gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _drop_manual(x, dim):
    return _local_slice(x, dim)


def _drop_fwd(x, dim):
    return _drop_manual(x, dim), None


def _drop_bwd(dim, _, ct):
    return (jax.lax.all_gather(ct, TENSOR_AXIS, axis=dim, tiled=True),)


_drop_manual.defvjp(_drop_fwd, _drop_bwd)


def gather_tokens(x: jax.Array, dim: int = 0) -> jax.Array:
    """All-gather ``x`` along ``dim`` across TP ranks (reference
    ``gather_tokens``; backward drops to the local chunk)."""
    mode = _axis_mode()
    if mode == "none":
        return x
    if mode == "manual":
        return _gather_manual(x, dim)
    # GSPMD: constrain replicated along dim — XLA materializes the gather
    return maybe_constrain(x, _spec(x, dim, sharded=False))


def drop_tokens(x: jax.Array, dim: int = 0) -> jax.Array:
    """Keep this rank's chunk of ``x`` along ``dim`` (reference
    ``drop_tokens``; backward all-gathers)."""
    mode = _axis_mode()
    if mode == "none":
        return x
    if mode == "manual":
        return _drop_manual(x, dim)
    return maybe_constrain(x, _spec(x, dim, sharded=True))
