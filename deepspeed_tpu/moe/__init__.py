from deepspeed_tpu.moe.layer import Experts, MoE, TopKGate
from deepspeed_tpu.moe.sharded_moe import (capacity, moe_dispatch_combine,
                                           top1_gating, top2_gating)
from deepspeed_tpu.moe.utils import (is_moe_param_path, moe_param_count,
                                     split_moe_params)

__all__ = [
    "MoE", "TopKGate", "Experts", "top1_gating", "top2_gating", "capacity",
    "moe_dispatch_combine", "split_moe_params", "moe_param_count",
    "is_moe_param_path",
]
