"""Config plumbing shared by all subsystem configs.

Analog of ``deepspeed/runtime/config_utils.py``: a pydantic base model with
deprecated-field aliasing plus the legacy ``get_scalar_param`` reader used by
the non-pydantic parts of the reference schema.
"""
from __future__ import annotations

from pydantic import BaseModel, ConfigDict


class DeepSpeedConfigModel(BaseModel):
    """Base for all config sections (reference: config_utils.py
    ``DeepSpeedConfigModel``). Unknown keys are rejected so typos fail fast,
    matching the reference's validation posture."""

    model_config = ConfigDict(extra="forbid", validate_assignment=True,
                              populate_by_name=True)

    def __init__(self, strict: bool = False, **data):
        # Reference semantics: passing None for a section means "defaults".
        data = {k: v for k, v in data.items() if v is not None}
        super().__init__(**data)


def get_scalar_param(param_dict: dict, param_name: str, param_default):
    """Legacy scalar reader (reference: config_utils.py ``get_scalar_param``)."""
    return param_dict.get(param_name, param_default)


def get_dict_param(param_dict: dict, param_name: str, param_default):
    return param_dict.get(param_name, param_default)
