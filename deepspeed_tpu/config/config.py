"""DeepSpeed-compatible JSON config for the TPU runtime.

Mirrors the schema consumed by ``deepspeed/runtime/config.py:702``
(``DeepSpeedConfig``): the batch-size triad, optimizer/scheduler sections,
fp16/bf16 precision sections, ``zero_optimization``, gradient clipping, and
logging knobs — plus a TPU-specific ``mesh`` section that replaces the
reference's implicit world-size/process-group wiring with explicit parallel
axis degrees (SURVEY §7.1).
"""
from __future__ import annotations

import json
from typing import Any, Dict, Literal, Optional, Union

from pydantic import ConfigDict, Field, model_validator

from deepspeed_tpu.config.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.comm.mesh import MeshConfig
from deepspeed_tpu.telemetry.config import TelemetryConfig
from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# Precision (reference: runtime/fp16 + bf16 config keys, runtime/config.py)
# ---------------------------------------------------------------------------

class FP16Config(DeepSpeedConfigModel):
    """fp16 section (reference keys: runtime/constants.py FP16_*)."""
    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    auto_cast: bool = False

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0.0


class BF16Config(DeepSpeedConfigModel):
    """bf16 section — the TPU default precision (native MXU dtype)."""
    enabled: bool = False


# ---------------------------------------------------------------------------
# ZeRO (reference: runtime/zero/config.py:76 DeepSpeedZeroConfig)
# ---------------------------------------------------------------------------

class OffloadParamConfig(DeepSpeedConfigModel):
    device: Literal["cpu", "nvme", "none"] = "cpu"
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    pin_memory: bool = False


class OffloadOptimizerConfig(DeepSpeedConfigModel):
    device: Literal["cpu", "nvme", "none"] = "cpu"
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    # TPU extension (not in the reference schema): how the host tier is
    # realized. "stream" keeps fp32 master+moments in the TPU host's
    # pinned memory and computes the update ON DEVICE inside the fused
    # jitted step, with XLA streaming the host<->HBM DMAs per leaf (the
    # PCIe-overlap role the reference's cpu_adam + copy streams play,
    # stage_1_and_2.py:1069-1219, without leaving XLA). "host" runs the
    # C++ SIMD Adam in process RAM (csrc/cpu_adam.cpp). "auto" picks
    # stream on TPU backends, host elsewhere.
    implementation: Literal["auto", "stream", "host"] = "auto"


class ZeroConfig(DeepSpeedConfigModel):
    """zero_optimization section.

    On TPU the stages are sharding policies over the ``data``(+``fsdp``) mesh
    axis rather than hook machinery (SURVEY §7.1):
      stage 0 — params/grads/opt-state replicated (plain DP)
      stage 1 — optimizer state (incl. fp32 master weights) sharded
      stage 2 — + gradients reduce-scattered to their shard
      stage 3 — + bf16 params sharded, gathered per-layer by XLA
    The prefetch/bucket/overlap knobs of the reference
    (runtime/zero/config.py) are accepted for config compatibility; XLA's
    latency-hiding scheduler performs the overlap they hand-tuned.
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    zero_hpz_partition_size: int = 1
    round_robin_gradients: bool = False
    ignore_unused_parameters: bool = True
    cpu_offload: Optional[bool] = None  # deprecated alias

    @model_validator(mode="after")
    def _resolve_deprecated(self):
        if self.cpu_offload and self.offload_optimizer is None:
            object.__setattr__(self, "offload_optimizer",
                               OffloadOptimizerConfig(device="cpu"))
        return self


# ---------------------------------------------------------------------------
# Optimizer / scheduler sections (reference: runtime/config.py optimizer keys)
# ---------------------------------------------------------------------------

class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "AdamW"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(DeepSpeedConfigModel):
    type: str = "WarmupLR"
    params: Dict[str, Any] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# Aux sections
# ---------------------------------------------------------------------------

class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: Optional[str] = None


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """activation_checkpointing section (reference:
    runtime/activation_checkpointing/checkpointing.py ``configure``).
    On TPU this maps onto jax.checkpoint policies; ``partition_activations``
    becomes sharding the saved residuals over the ``tensor``/``seq`` axes."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class FlopsProfilerConfig(DeepSpeedConfigModel):
    """flops_profiler section (reference profiling/config.py)."""
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class AMPConfig(DeepSpeedConfigModel):
    """``amp`` section (reference runtime/constants.py:177-192: Apex AMP
    pass-through kwargs). Apex is CUDA-only; on TPU ``amp.enabled`` maps to
    native bf16 mixed precision (fp32 master + bf16 compute) — the same
    contract O1/O2 provide. Unknown passthrough kwargs are surfaced, not
    silently swallowed."""
    enabled: bool = False
    opt_level: Literal["O0", "O1", "O2", "O3"] = "O1"

    model_config = ConfigDict(extra="allow", validate_assignment=True,
                              populate_by_name=True)


class EigenvalueConfig(DeepSpeedConfigModel):
    """``eigenvalue`` section (reference runtime/config.py:540
    get_eigenvalue_config) — drives MoQ precision switching. The reference
    asserts this off at v0.8.0 ("temporarily disabled"); here it works."""
    enabled: bool = False
    verbose: bool = False
    max_iter: int = Field(100, ge=1)
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = Field(1, ge=1)
    layer_name: str = ""
    layer_num: int = Field(0, ge=0)


class DataTypesConfig(DeepSpeedConfigModel):
    """``data_types`` section (reference runtime/constants.py:389-394):
    dtype used for the gradient-accumulation buffer under GAS."""
    grad_accum_dtype: Optional[Literal["fp32", "fp16", "bf16"]] = None


class CheckpointConfig(DeepSpeedConfigModel):
    """``checkpoint`` section. Beyond the reference keys, the integrity
    knobs drive the verified atomic-commit protocol
    (runtime/checkpointing.py; docs/training.md "Fault-tolerant training
    & verified checkpoints"): every published tag carries a per-file
    sha256 manifest, ``latest`` advances only after the manifest
    verifies, and load walks a fallback ladder past corrupted tags."""
    tag_validation: Literal["Ignore", "Warn", "Fail", "ignore", "warn", "fail"] = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = Field(default_factory=dict)
    # "sync" (Torch engine analog) | "async"/"nebula" (background persist)
    engine: Literal["sync", "async", "nebula", "orbax", "torch"] = "sync"
    # integrity manifest: hash every file at publish, re-verify before
    # 'latest' advances, verify again (deep) before any load; false
    # restores the reference's trust-the-directory behavior
    verify: bool = True
    # bounded retention: keep the newest N committed tags, GC the rest
    # after each publish (reclaimed bytes -> ckpt_gc_reclaimed_total);
    # 0 keeps everything
    keep_last: int = Field(0, ge=0)

    @model_validator(mode="after")
    def _keep_last_needs_verify(self):
        # retention GC walks committed (manifest-bearing) tags; with
        # verify=false no manifest is ever written, so keep_last would
        # silently never delete anything — reject the inert combination
        if self.keep_last > 0 and not self.verify:
            raise ValueError(
                "checkpoint.keep_last requires checkpoint.verify: "
                "retention GC only considers committed (manifest-"
                "bearing) tags, and verify=false writes no manifests")
        return self


class ResilienceConfig(DeepSpeedConfigModel):
    """``resilience`` section — the TrainingSupervisor's policy
    (runtime/resilience.py; docs/training.md "Fault-tolerant training &
    verified checkpoints"): checkpoint cadence, bounded restart budget
    with exponential backoff, and the NaN/data-stall tripwires. The
    supervisor guarantees forward progress or a loud terminal
    ``failed`` — never a hang. Opt-in is by CONSTRUCTION — wrapping the
    loop in a ``TrainingSupervisor`` arms it; there is deliberately no
    ``enabled`` flag here, because the engine does not own the train
    loop and a config bit that silently did nothing would be worse
    than none."""
    # save a verified checkpoint every N supervised steps (an initial
    # one is always written before step 0 so rollback always has a rung)
    checkpoint_every: int = Field(50, ge=1)
    # restarts allowed across the whole run before the supervisor ends
    # in 'failed' (each fault kind counts against the same budget)
    max_restarts: int = Field(3, ge=0)
    # exponential backoff between a fault and its restart:
    # min(backoff_base_s * 2**(restart-1), backoff_max_s)
    backoff_base_s: float = Field(0.5, ge=0.0)
    backoff_max_s: float = Field(30.0, ge=0.0)
    # a batch fetch slower than this is a data_stall fault (None = no
    # data tripwire)
    data_stall_timeout_s: Optional[float] = Field(None, gt=0.0)
    # treat a non-finite loss (or a numerics-watch non-finite step) as a
    # nan_burst fault and roll back; false lets NaN steps through to the
    # caller unchanged
    restart_on_nan: bool = True


class DeepSpeedConfig:
    """Top-level config (reference: runtime/config.py:702).

    Accepts a dict or a path to a JSON file. Resolves the
    train_batch_size = micro_batch * grad_accum * dp_world_size triad exactly
    as ``_set_batch_related_parameters`` (runtime/config.py:942) does.
    """

    def __init__(self, config: Union[str, dict], dp_world_size: Optional[int] = None):
        if isinstance(config, str):
            with open(config) as f:
                self._param_dict = json.load(f)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise ValueError(f"expected dict or json path, got {type(config)}")

        pd = self._param_dict
        self._validate_keys(pd)
        self.train_batch_size: Optional[int] = pd.get("train_batch_size")
        self.train_micro_batch_size_per_gpu: Optional[int] = pd.get(
            "train_micro_batch_size_per_gpu")
        self.gradient_accumulation_steps: Optional[int] = pd.get(
            "gradient_accumulation_steps")
        self.steps_per_print: int = pd.get("steps_per_print", 10)
        self.wall_clock_breakdown: bool = pd.get("wall_clock_breakdown", False)
        self.memory_breakdown: bool = pd.get("memory_breakdown", False)
        self.prescale_gradients: bool = pd.get("prescale_gradients", False)
        self.gradient_predivide_factor: float = pd.get("gradient_predivide_factor", 1.0)
        self.gradient_clipping: float = pd.get("gradient_clipping", 0.0)
        self.dump_state: bool = pd.get("dump_state", False)
        self.seed: int = pd.get("seed", 42)

        self.fp16 = FP16Config(**pd.get("fp16", {}))
        self.bf16 = BF16Config(**pd.get("bf16", pd.get("bfloat16", {})))
        self.zero_config = ZeroConfig(**pd.get("zero_optimization", {}))
        self.optimizer = (OptimizerConfig(**pd["optimizer"])
                          if "optimizer" in pd else None)
        self.scheduler = (SchedulerConfig(**pd["scheduler"])
                          if "scheduler" in pd else None)
        self.comms_logger = CommsLoggerConfig(**pd.get("comms_logger", {}))
        self.tensorboard = TensorBoardConfig(**pd.get("tensorboard", {}))
        self.wandb = WandbConfig(**pd.get("wandb", {}))
        self.csv_monitor = CSVConfig(**pd.get("csv_monitor", {}))
        # metrics registry + optional scrape endpoint (shared schema with
        # DeepSpeedInferenceConfig; docs/observability.md)
        self.telemetry = TelemetryConfig(**pd.get("telemetry", {}))
        self.activation_checkpointing = ActivationCheckpointingConfig(
            **pd.get("activation_checkpointing", {}))
        self.checkpoint_config = CheckpointConfig(**pd.get("checkpoint", {}))
        # fault-tolerant training supervisor (runtime/resilience.py)
        self.resilience = ResilienceConfig(**pd.get("resilience", {}))
        self.mesh = MeshConfig(**pd.get("mesh", {}))
        self.compile_cache_dir: Optional[str] = pd.get("compile_cache_dir")
        self.flops_profiler = FlopsProfilerConfig(
            **pd.get("flops_profiler", {}))
        # data-efficiency: either the modern nested section or the legacy
        # top-level curriculum_learning (engine.py:1807)
        de = pd.get("data_efficiency", {})
        self.curriculum_learning: dict = pd.get(
            "curriculum_learning",
            de.get("data_sampling", {}).get("curriculum_learning", {}))

        # communication_data_type (reference constants.py:119): the DP
        # gradient-reduction dtype; engine maps it onto the accumulation
        # buffer (reduction happens at the accumulated dtype under GSPMD)
        cdt = pd.get("communication_data_type")
        if cdt is not None:
            cdt = {"fp32": "fp32", "float32": "fp32", "fp16": "fp16",
                   "float16": "fp16", "bf16": "bf16",
                   "bfloat16": "bf16"}.get(str(cdt))
            if cdt is None:
                raise ValueError(
                    f"communication_data_type must be fp32/fp16/bf16, "
                    f"got {pd.get('communication_data_type')!r}")
        self.communication_data_type: Optional[str] = cdt
        self.amp = AMPConfig(**pd.get("amp", {}))
        # validate the comm-dtype/accum-dtype pairing HERE — a conflict
        # must not survive until the first train_batch of a pod job
        _acc = pd.get("data_types", {}).get("grad_accum_dtype")
        if _acc and cdt and _acc != cdt:
            raise ValueError(
                f"data_types.grad_accum_dtype={_acc!r} conflicts with "
                f"communication_data_type={cdt!r} — they name the same "
                "buffer (grads reduce at their accumulated dtype under "
                "GSPMD)")
        self.eigenvalue = EigenvalueConfig(**pd.get("eigenvalue", {}))
        self.data_types = DataTypesConfig(**pd.get("data_types", {}))
        self.sparse_gradients: bool = pd.get("sparse_gradients", False)
        # parsed-section parity with reference DeepSpeedConfig.
        # compression_config: consumed by the engine's MoQ setup
        # (MoQConfig.from_compression_config) and by user-driven
        # compression.init_compression
        self.compression_config: dict = pd.get("compression_training", {})

        if self.fp16.enabled and self.bf16.enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")
        if self.amp.enabled:
            if self.fp16.enabled or self.bf16.enabled:
                raise ValueError(
                    "amp is mutually exclusive with fp16/bf16 (the "
                    "reference engine has the same restriction)")
            if self.amp.opt_level == "O3":
                raise ValueError(
                    "amp opt_level O3 (pure half, no master weights) is "
                    "numerically unsafe and unsupported; use O1/O2")
            extra = {k: v for k, v in pd.get("amp", {}).items()
                     if k not in ("enabled", "opt_level")}
            if extra:
                logger.warning(
                    "amp passthrough kwargs %s are Apex-specific and have "
                    "no TPU meaning; amp maps to native bf16 mixed "
                    "precision here", sorted(extra))
        if self.eigenvalue.enabled and not self.eigenvalue.layer_name:
            raise ValueError("eigenvalue.enabled requires layer_name "
                             "(reference eigenvalue.py asserts the same)")

        self.zero_enabled = self.zero_config.stage > 0
        self.zero_optimization_stage = self.zero_config.stage

        if dp_world_size is not None:
            self.resolve_batch_config(dp_world_size)

    KNOWN_KEYS = frozenset({
        "train_batch_size", "train_micro_batch_size_per_gpu",
        "gradient_accumulation_steps", "steps_per_print",
        "wall_clock_breakdown", "memory_breakdown", "prescale_gradients",
        "gradient_predivide_factor", "gradient_clipping", "dump_state",
        "seed", "fp16", "bf16", "bfloat16", "zero_optimization", "optimizer",
        "scheduler", "comms_logger", "tensorboard", "wandb", "csv_monitor",
        "activation_checkpointing", "checkpoint", "mesh",
        "compile_cache_dir", "flops_profiler", "monitor", "elasticity",
        "autotuning", "compression_training", "data_efficiency",
        "curriculum_learning", "aio", "sparse_attention",
        "zero_allow_untested_optimizer", "communication_data_type",
        "sparse_gradients", "amp", "pipeline", "inference", "data_types",
        "eigenvalue", "progressive_layer_drop", "nebula", "telemetry",
        "resilience",
    })

    @classmethod
    def _validate_keys(cls, pd: dict) -> None:
        """Reject unknown top-level keys — typos must fail loudly (the
        reference warns via pydantic extra-field handling; we error, since a
        silently-ignored ``zero_optimizatoin`` can cost a training run)."""
        import difflib
        unknown = [k for k in pd if k not in cls.KNOWN_KEYS]
        if unknown:
            hints = []
            for k in unknown:
                close = difflib.get_close_matches(k, cls.KNOWN_KEYS, n=1)
                hints.append(f"{k!r}" + (f" (did you mean {close[0]!r}?)"
                                         if close else ""))
            raise ValueError(
                f"unknown config key(s): {', '.join(hints)}")

    # -- batch triad (reference: runtime/config.py:942 + assertions :918) ----
    def resolve_batch_config(self, dp_world_size: int) -> None:
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= dp_world_size
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // dp_world_size
            micro_batch //= grad_acc
        elif micro_batch is not None and grad_acc is not None:
            train_batch = micro_batch * grad_acc * dp_world_size
        elif train_batch is not None:
            grad_acc = 1
            micro_batch = train_batch // dp_world_size
        elif micro_batch is not None:
            train_batch = micro_batch * dp_world_size
            grad_acc = 1
        else:
            raise ValueError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

        if train_batch <= 0 or micro_batch <= 0 or grad_acc <= 0:
            raise ValueError(
                f"batch config resolved to non-positive values: "
                f"train={train_batch} micro={micro_batch} gas={grad_acc}")
        if train_batch != micro_batch * grad_acc * dp_world_size:
            raise ValueError(
                f"Check batch related parameters. train_batch_size is not equal"
                f" to micro_batch_per_gpu * gradient_acc_step * world_size "
                f"{train_batch} != {micro_batch} * {grad_acc} * {dp_world_size}")

        self.train_batch_size = train_batch
        self.train_micro_batch_size_per_gpu = micro_batch
        self.gradient_accumulation_steps = grad_acc
        logger.info(f"batch config: global={train_batch} micro={micro_batch} "
                    f"gas={grad_acc} dp={dp_world_size}")

    @property
    def precision_dtype(self) -> str:
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        if self.amp.enabled and self.amp.opt_level in ("O1", "O2"):
            # Apex O1/O2 ≈ fp32 master + half compute; TPU-native half is
            # bf16 (no loss scaling needed — amp's dynamic scaler is an
            # fp16 artifact). O0 is Apex's fp32-passthrough baseline mode
            # and stays fp32.
            return "bfloat16"
        return "float32"

    def print_config(self) -> None:
        logger.info(json.dumps(self._param_dict, indent=2, sort_keys=True))
