"""LLaMA model family (flax) — modern decoder training, TPU-first.

The reference serves the LLaMA family through inference policy injection
(deepspeed/module_inject — our ``module_inject/policies.py`` carries the
LLaMA/Mistral policies) and trains it through the Megatron-DeepSpeed
stack. This module is the training-side counterpart of those policies: a
functional flax decoder with the LLaMA architecture — RMSNorm, rotary
position embeddings, grouped-query attention, SwiGLU MLP, no biases —
matching HuggingFace ``LlamaForCausalLM`` numerics (the de-facto weight
layout; pinned by tests/test_llama_model.py against the torch model).

TPU-first choices mirror models/gpt2.py: bf16 matmuls with fp32-stat
norms, the Pallas flash-attention path with its remat-visible
``flash_attn_out`` tag, Megatron-style tensor-parallel PartitionSpecs
(column-parallel q/k/v/gate/up, row-parallel o/down, vocab-parallel
embedding), and ring/Ulysses sequence parallelism expressed as global-view
SPMD (positions are global under jit, so RoPE needs no per-shard offset
bookkeeping).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.mesh import DATA_AXES
from deepspeed_tpu.comm.mesh import seq_axis_active as _seq_axis_active
from deepspeed_tpu.ops.int8_training import (lm_logits,
                                              maybe_switchback)
from deepspeed_tpu.utils.jit import instance_cached_jit
from deepspeed_tpu.utils.sharding import maybe_constrain as _maybe_constrain


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_positions: int = 2048
    n_embd: int = 2048
    n_layer: int = 16
    n_head: int = 16
    n_kv_head: int = 16            # < n_head => grouped-query attention
    intermediate_size: int = 5504  # SwiGLU hidden (~8/3 * n_embd rounded)
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    remat: bool = True
    use_flash_attention: bool = True
    # flash tile-size override (0 = kernel default 256; bench --flash-block)
    flash_block: int = 0
    sequence_parallel: bool = False
    sp_mode: str = "ring"
    # Mixtral-style MoE: num_experts > 0 replaces the SwiGLU FFN of the
    # layers in ``moe_layers`` (None → EVERY layer, the Mixtral layout)
    # with top-k gated SwiGLU experts sharded over the data/fsdp axes.
    # Gate aux loss folds into loss_fn with moe_aux_weight.
    num_experts: int = 0
    moe_layers: Optional[tuple] = None
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # SwitchBack int8 projections (ops/int8_training.py; see GPT2Config)
    int8_training: bool = False

    def __post_init__(self):
        if self.n_head % self.n_kv_head:
            raise ValueError(f"n_head={self.n_head} must be divisible by "
                             f"n_kv_head={self.n_kv_head}")
        if self.sp_mode not in ("ring", "ulysses"):
            raise ValueError(f"sp_mode must be 'ring' or 'ulysses', got "
                             f"{self.sp_mode!r}")
        if self.num_experts > 0:
            layers = self.moe_layer_set
            if not layers:
                raise ValueError("num_experts > 0 needs at least one MoE "
                                 "layer (moe_layers is empty)")
            bad = sorted(i for i in layers if not 0 <= i < self.n_layer)
            if bad:
                raise ValueError(f"moe_layers {bad} out of range for "
                                 f"n_layer={self.n_layer}")

    @property
    def moe_layer_set(self) -> frozenset:
        if self.num_experts <= 0:
            return frozenset()
        if self.moe_layers is not None:
            return frozenset(self.moe_layers)
        return frozenset(range(self.n_layer))

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


PRESETS: Dict[str, dict] = {
    # HF config shapes for the common ladder
    "llama-tiny": dict(vocab_size=512, n_positions=256, n_embd=128,
                       n_layer=2, n_head=4, n_kv_head=2,
                       intermediate_size=352),
    "llama-1b": dict(n_embd=2048, n_layer=16, n_head=16, n_kv_head=16,
                     intermediate_size=5504),
    "llama-3b": dict(n_embd=2560, n_layer=26, n_head=20, n_kv_head=20,
                     intermediate_size=6912),
    "llama-7b": dict(n_embd=4096, n_layer=32, n_head=32, n_kv_head=32,
                     intermediate_size=11008, n_positions=4096),
    # mistral-style GQA variant
    "llama-7b-gqa": dict(n_embd=4096, n_layer=32, n_head=32, n_kv_head=8,
                         intermediate_size=14336, n_positions=4096),
    # Mixtral layout: GQA + top-2 gated-SwiGLU experts in EVERY layer
    "mixtral-tiny": dict(vocab_size=512, n_positions=256, n_embd=128,
                         n_layer=2, n_head=4, n_kv_head=2,
                         intermediate_size=352, num_experts=4,
                         moe_capacity_factor=2.0),
    "mixtral-8x7b": dict(n_embd=4096, n_layer=32, n_head=32, n_kv_head=8,
                         intermediate_size=14336, n_positions=4096,
                         num_experts=8, moe_top_k=2),
}


def config_for(name: str, **overrides) -> LlamaConfig:
    if name not in PRESETS:
        raise ValueError(f"unknown preset {name!r}: {sorted(PRESETS)}")
    return LlamaConfig(**{**PRESETS[name], **overrides})


def _rms_norm(x, weight, eps):
    """RMSNorm with fp32 statistics (HF LlamaRMSNorm semantics: variance
    in fp32, scaled output cast back to the input dtype)."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(dt) * weight.astype(dt)


def _rope(q, k, positions, theta):
    """HF rotate-half rotary embedding. q/k ``[B, T, H, D]``, positions
    ``[T]`` (global under jit — sequence sharding slices them)."""
    D = q.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    ang = positions[:, None].astype(jnp.float32) * inv[None, :]  # [T, D/2]
    cos = jnp.concatenate([jnp.cos(ang), jnp.cos(ang)], -1)[None, :, None]
    sin = jnp.concatenate([jnp.sin(ang), jnp.sin(ang)], -1)[None, :, None]

    def rot(x):
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([-x2, x1], -1)

    qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
    q_out = qf * cos + rot(qf) * sin
    k_out = kf * cos + rot(kf) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        B, T, C = x.shape
        H, HKV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
        dense = lambda feat, name: nn.Dense(  # noqa: E731
            feat, use_bias=False, dtype=cfg.dtype, name=name,
            dot_general=maybe_switchback(cfg.int8_training))
        q = dense(H * D, "wq")(x).reshape(B, T, H, D)
        k = dense(HKV * D, "wk")(x).reshape(B, T, HKV, D)
        v = dense(HKV * D, "wv")(x).reshape(B, T, HKV, D)
        q, k = _rope(q, k, jnp.arange(T), cfg.rope_theta)
        sp_active = cfg.sequence_parallel and _seq_axis_active()
        if sp_active:
            from deepspeed_tpu.comm.mesh import get_global_mesh
        if HKV != H and sp_active and cfg.sp_mode == "ulysses":
            if HKV % get_global_mesh().shape["seq"]:
                # Ulysses' head all-to-all only preserves GQA group
                # alignment when kv heads split evenly across the seq
                # axis; otherwise fall back to expanded k/v. Ring, flash,
                # and the reference path always consume unexpanded k/v.
                k = jnp.repeat(k, H // HKV, axis=2)
                v = jnp.repeat(v, H // HKV, axis=2)

        if sp_active:
            if cfg.sp_mode == "ulysses":
                from deepspeed_tpu.ops.ulysses_attention import (
                    ulysses_self_attention)
                y = ulysses_self_attention(q, k, v, get_global_mesh(),
                                           block=cfg.flash_block)
            else:
                from deepspeed_tpu.ops.ring_attention import (
                    ring_self_attention)
                y = ring_self_attention(q, k, v, get_global_mesh())
        elif cfg.use_flash_attention:
            from deepspeed_tpu.ops.attention import causal_attention
            y = causal_attention(q, k, v, block_q=cfg.flash_block,
                                 block_k=cfg.flash_block)
        else:
            from deepspeed_tpu.ops.attention import (
                causal_attention_reference)
            y = causal_attention_reference(q, k, v)
        return dense(C, "wo")(y.reshape(B, T, H * D))


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda feat, name: nn.Dense(  # noqa: E731
            feat, use_bias=False, dtype=cfg.dtype, name=name,
            dot_general=maybe_switchback(cfg.int8_training))
        g = dense(cfg.intermediate_size, "gate")(x)
        u = dense(cfg.intermediate_size, "up")(x)
        return dense(cfg.n_embd, "down")(jax.nn.silu(g) * u)


class LlamaBlock(nn.Module):
    """Decoder block. With ``moe=True`` (Mixtral layout) the FFN slot
    holds top-k gated-SwiGLU experts and ``__call__`` returns
    ``(x, l_aux)`` — one class for both so the norm/attention/residual
    structure cannot drift. ``train`` is static under remat."""
    config: LlamaConfig
    moe: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        ln1 = self.param("ln_attn", nn.initializers.ones, (cfg.n_embd,),
                         jnp.float32)
        ln2 = self.param("ln_mlp", nn.initializers.ones, (cfg.n_embd,),
                         jnp.float32)
        x = x + LlamaAttention(cfg, name="attn")(
            _rms_norm(x, ln1, cfg.rms_eps))
        h = _rms_norm(x, ln2, cfg.rms_eps)
        if self.moe:
            from deepspeed_tpu.moe.layer import MoE
            B, T, C = x.shape
            y, l_aux, _ = MoE(hidden_size=C, num_experts=cfg.num_experts,
                              ffn_hidden_size=cfg.intermediate_size,
                              k=cfg.moe_top_k,
                              capacity_factor=cfg.moe_capacity_factor,
                              eval_capacity_factor=cfg.moe_capacity_factor,
                              min_capacity=4, dtype=cfg.dtype,
                              activation=jax.nn.silu, gated_experts=True,
                              int8_training=cfg.int8_training,
                              name="moe")(h.reshape(B * T, C), train=train)
            return x + y.reshape(B, T, C), l_aux
        return x + LlamaMLP(cfg, name="mlp")(h)


class Llama(nn.Module):
    """Causal LM trunk + head. ``__call__`` returns logits [B, T, V] —
    or ``(logits, l_aux_total)`` when the config has MoE layers."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, train: bool = False):
        cfg = self.config
        B, T = input_ids.shape
        embed = self.param("embed", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.n_embd), jnp.float32)
        # gather rows then cast (same HBM-traffic reasoning as gpt2.py)
        x = embed[input_ids].astype(cfg.dtype)
        x = _maybe_constrain(x, P(DATA_AXES, "seq", None))

        block = LlamaBlock
        if cfg.remat:
            policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_attn_out"))
            # train is control flow (MoE capacity mode), not data — static
            # under the remat trace (argnum 2; the instance is 0)
            block = nn.remat(block, prevent_cse=False, policy=policy,
                             static_argnums=(2,))
        moe_set = cfg.moe_layer_set
        l_aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layer):
            if i in moe_set:
                x, l_aux = block(cfg, moe=True,
                                 name=f"layers_{i}")(x, train)
                l_aux_total = l_aux_total + l_aux.astype(jnp.float32)
            else:
                x = block(cfg, name=f"layers_{i}")(x, train)

        ln_f = self.param("ln_f", nn.initializers.ones, (cfg.n_embd,),
                          jnp.float32)
        x = _rms_norm(x, ln_f, cfg.rms_eps)
        if cfg.tie_embeddings:
            w_head = embed
        else:
            w_head = self.param("lm_head", nn.initializers.normal(0.02),
                                (cfg.vocab_size, cfg.n_embd), jnp.float32)
        logits = lm_logits(x, w_head.astype(cfg.dtype),
                           cfg.int8_training)
        if moe_set:
            return logits, l_aux_total
        return logits


class LlamaLMModel:
    """Engine-facing wrapper: init + loss_fn + tp_specs (the same contract
    GPT2LMModel satisfies, so every engine feature — ZeRO stages, offload,
    precision modes, curriculum — applies unchanged)."""

    def __init__(self, config: LlamaConfig):
        self.config = config
        self.module = Llama(config)

    def init(self, rng, example_batch=None, batch_size: int = 2,
             seq_len=None):
        seq_len = seq_len or min(self.config.n_positions, 128)
        if example_batch is not None:
            ids = example_batch["input_ids"]
        else:
            ids = jnp.zeros((batch_size, seq_len), jnp.int32)
        # one compiled executable, wrapper cached on the instance
        # (utils/jit.py): no per-op dispatch round trips at init
        return instance_cached_jit(self, self.module.init)(
            rng, ids)["params"]

    def apply(self, params, input_ids, deterministic=True, rngs=None):
        """Returns logits; with MoE layers, ``(logits, l_aux_total)``."""
        return self.module.apply({"params": params}, input_ids,
                                 train=not deterministic, rngs=rngs)

    def loss_fn(self, params, batch, rng=None):
        cfg = self.config
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        rngs = ({"gating": jax.random.fold_in(rng, 1)}
                if (rng is not None and cfg.num_experts > 0) else None)
        out = self.apply(params, input_ids, deterministic=rng is None,
                         rngs=rngs)
        l_aux = None
        if cfg.num_experts > 0:
            logits, l_aux = out
        else:
            logits = out
        if labels is None:
            labels = input_ids[:, 1:]
            logits = logits[:, :-1]
        logits = logits.astype(jnp.float32)
        # lse - gold: no materialized [B, T, V] log-prob tensor
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None],
                                   axis=-1)[..., 0]
        nll = lse - gold
        mask = (labels >= 0) & (labels < self.config.vocab_size)
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
        if l_aux is not None:
            loss = loss + cfg.moe_aux_weight * l_aux
        return loss

    def tp_specs(self):
        """Megatron placement: q/k/v/gate/up column-parallel, o/down
        row-parallel, embedding + head vocab-parallel; MoE experts
        EP-sharded on their leading expert dim."""
        cfg = self.config
        block = {
            "ln_attn": P(), "ln_mlp": P(),
            "attn": {"wq": {"kernel": P(None, "tensor")},
                     "wk": {"kernel": P(None, "tensor")},
                     "wv": {"kernel": P(None, "tensor")},
                     "wo": {"kernel": P("tensor", None)}},
            "mlp": {"gate": {"kernel": P(None, "tensor")},
                    "up": {"kernel": P(None, "tensor")},
                    "down": {"kernel": P("tensor", None)}},
        }
        moe_set = cfg.moe_layer_set
        if moe_set:
            from deepspeed_tpu.moe.layer import MoE
            moe_block = dict(block)
            del moe_block["mlp"]
            moe_block["moe"] = MoE.tp_specs(gated=True)
        specs: dict = {"embed": P("tensor", None), "ln_f": P()}
        if not cfg.tie_embeddings:
            specs["lm_head"] = P("tensor", None)
        for i in range(cfg.n_layer):
            specs[f"layers_{i}"] = moe_block if i in moe_set else block
        return specs

    def param_count(self, params) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))

    def flops_per_token(self) -> float:
        """~6 * N_active_params per token; MoE layers count top_k expert
        FFNs (active compute), like GPT2LMModel.flops_per_token."""
        cfg = self.config
        attn = (2 * cfg.n_embd * (cfg.n_head * cfg.head_dim)           # q,o
                + 2 * cfg.n_embd * (cfg.n_kv_head * cfg.head_dim))     # k,v
        ffn = 3 * cfg.n_embd * cfg.intermediate_size
        n_moe = len(cfg.moe_layer_set)
        n = (cfg.vocab_size * cfg.n_embd * (1 if cfg.tie_embeddings else 2)
             + cfg.n_layer * attn
             + (cfg.n_layer - n_moe) * ffn
             + n_moe * cfg.moe_top_k * ffn)
        return 6.0 * n


def params_from_hf(hf_state_dict, cfg: LlamaConfig):
    """Map a HuggingFace ``LlamaForCausalLM`` or ``MixtralForCausalLM``
    state dict onto this model's param tree (torch [out, in] kernels
    transpose to flax [in, out]). MoE layers read the Mixtral layout
    (``block_sparse_moe.gate`` + per-expert ``w1/w2/w3``, stacked on the
    leading expert dim: w1→wg gate, w3→wi up, w2→wo down). Accepts torch
    tensors or numpy arrays."""
    import numpy as np

    def raw(name):
        w = hf_state_dict[name]
        return np.asarray(w.detach().cpu().numpy()
                          if hasattr(w, "detach") else w, np.float32)

    def t(name, transpose=False):
        w = raw(name)
        return jnp.asarray(w.T if transpose else w)

    def moe_subtree(p):
        E = cfg.num_experts
        ex = f"{p}block_sparse_moe.experts."
        # torch per-expert [out, in] → stacked flax [E, in, out]
        stack = lambda w: jnp.asarray(np.stack(  # noqa: E731
            [raw(f"{ex}{e}.{w}.weight").T for e in range(E)]))
        return {
            "gate": {"wg": t(p + "block_sparse_moe.gate.weight", True)},
            "experts": {"wg": stack("w1"), "wo": stack("w2"),
                        "wi": stack("w3")},
        }

    moe_set = cfg.moe_layer_set
    params: dict = {"embed": t("model.embed_tokens.weight"),
                    "ln_f": t("model.norm.weight")}
    if not cfg.tie_embeddings:
        params["lm_head"] = t("lm_head.weight")
    for i in range(cfg.n_layer):
        p = f"model.layers.{i}."
        layer = {
            "ln_attn": t(p + "input_layernorm.weight"),
            "ln_mlp": t(p + "post_attention_layernorm.weight"),
            "attn": {
                "wq": {"kernel": t(p + "self_attn.q_proj.weight", True)},
                "wk": {"kernel": t(p + "self_attn.k_proj.weight", True)},
                "wv": {"kernel": t(p + "self_attn.v_proj.weight", True)},
                "wo": {"kernel": t(p + "self_attn.o_proj.weight", True)},
            },
        }
        if i in moe_set:
            layer["moe"] = moe_subtree(p)
        else:
            layer["mlp"] = {
                "gate": {"kernel": t(p + "mlp.gate_proj.weight", True)},
                "up": {"kernel": t(p + "mlp.up_proj.weight", True)},
                "down": {"kernel": t(p + "mlp.down_proj.weight", True)},
            }
        params[f"layers_{i}"] = layer
    return params
