"""Pipelined GPT-2 — the flagship model on the ``pipe`` mesh axis.

The reference expresses pipelined GPT as a ``PipelineModule`` of LayerSpecs
interpreted rank-by-rank (``runtime/pipe/module.py:85``); here the decoder
stack is a single stacked-parameter pytree (leading dim = n_layer) driven
through the compiled scan+ppermute executor
(deepspeed_tpu/parallel/pipe/pipeline.py). Embedding and LM head run outside
the pipelined region — replicated over ``pipe``, sharded over
data/tensor/seq like any other layer. Weight tying (wte = unembedding) is
structural, so the reference's tied-weight allreduce
(runtime/pipe/module.py:420) is subsumed by autodiff.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.int8_training import lm_logits
from deepspeed_tpu.models.gpt2 import Block, GPT2Config, _maybe_constrain
from deepspeed_tpu.parallel.pipe.pipeline import pipeline_apply

from deepspeed_tpu.comm.mesh import DATA_AXES  # noqa: F401


class GPT2PipeModel:
    """Engine-facing pipelined GPT-2: init + loss_fn + tp_specs.

    ``num_microbatches`` splits the per-step batch inside the pipeline
    (the analog of PipelineEngine's micro_batches = gradient accumulation
    steps, runtime/pipe/engine.py:294).
    """

    def __init__(self, config: GPT2Config, num_microbatches: int = 4):
        if config.dropout > 0.0:
            raise NotImplementedError(
                "GPT2PipeModel does not thread dropout rngs through the "
                "pipelined scan yet; set dropout=0.0 (the reference's large-"
                "model GPT configs train without dropout too)")
        self.config = config
        self.num_microbatches = num_microbatches
        self._block = Block(config)

    # -- init ---------------------------------------------------------------
    def init(self, rng, batch_size: int = 2, seq_len: Optional[int] = None):
        cfg = self.config
        seq_len = seq_len or min(cfg.n_positions, 128)
        k_wte, k_wpe, k_blocks = jax.random.split(rng, 3)
        wte = jax.random.normal(k_wte, (cfg.padded_vocab_size, cfg.n_embd),
                                jnp.float32) * 0.02
        wpe = jax.random.normal(k_wpe, (cfg.n_positions, cfg.n_embd),
                                jnp.float32) * 0.01
        dummy = jnp.zeros((1, seq_len, cfg.n_embd), cfg.dtype)

        def init_one(key):
            return self._block.init(key, dummy)["params"]

        blocks = jax.vmap(init_one)(jax.random.split(k_blocks, cfg.n_layer))
        ln_f = {"scale": jnp.ones((cfg.n_embd,), jnp.float32),
                "bias": jnp.zeros((cfg.n_embd,), jnp.float32)}
        return {"wte": wte, "wpe": wpe, "blocks": blocks, "ln_f": ln_f}

    # -- forward ------------------------------------------------------------
    def _block_fn(self, layer_params, h):
        return self._block.apply({"params": layer_params}, h)

    def apply(self, params, input_ids):
        cfg = self.config
        B, T = input_ids.shape
        # gather rows THEN cast; static position slice (models/gpt2.py)
        x = params["wte"][input_ids].astype(cfg.dtype) + \
            params["wpe"][:T].astype(cfg.dtype)[None]
        x = _maybe_constrain(x, P(DATA_AXES, "seq", None))
        x = pipeline_apply(self._block_fn, params["blocks"], x,
                           num_microbatches=self.num_microbatches,
                           remat=cfg.remat)
        # final LN in fp32 accumulation, same as the fused reference kernel
        mean = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
        var = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
        x32 = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + 1e-5)
        x = (x32 * params["ln_f"]["scale"] +
             params["ln_f"]["bias"]).astype(cfg.dtype)
        return lm_logits(x, params["wte"].astype(cfg.dtype),
                         cfg.int8_training)

    def loss_fn(self, params, batch, rng=None):
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        logits = self.apply(params, input_ids)
        if labels is None:
            labels = input_ids[:, 1:]
            logits = logits[:, :-1]
        logits = logits.astype(jnp.float32)
        # lse - gold (see models/gpt2.py loss_fn): no [B, T, V] fp32
        # log-prob tensor
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None],
                                   axis=-1)[..., 0]
        nll = lse - gold
        mask = (labels >= 0) & (labels < self.config.vocab_size)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)

    # -- sharding -----------------------------------------------------------
    def tp_specs(self):
        """Stacked-block leaves get ``pipe`` on dim 0; within-layer dims carry
        the same Megatron TP placement as the unpipelined model."""
        def pp(*rest):
            return P("pipe", *rest)
        block = {
            "ln_1": {"scale": pp(), "bias": pp()},
            "ln_2": {"scale": pp(), "bias": pp()},
            "attn": {
                "c_attn": {"kernel": pp(None, "tensor"), "bias": pp("tensor")},
                "c_proj": {"kernel": pp("tensor", None), "bias": pp()},
            },
            "mlp": {
                "c_fc": {"kernel": pp(None, "tensor"), "bias": pp("tensor")},
                "c_proj": {"kernel": pp("tensor", None), "bias": pp()},
            },
        }
        return {"wte": P("tensor", None), "wpe": P(), "blocks": block,
                "ln_f": {"scale": P(), "bias": P()}}

    def param_count(self, params) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))

    def flops_per_token(self) -> float:
        cfg = self.config
        n = (cfg.padded_vocab_size * cfg.n_embd
             + cfg.n_positions * cfg.n_embd
             + cfg.n_layer * (12 * cfg.n_embd ** 2))
        return 6.0 * n
