"""BERT pre-training model family — the reference's flagship training bench.

The reference's headline training kernel is the BERT encoder layer
(``ops/transformer/transformer.py:459``; benchmarked via BingBertSquad and
bert-bench, SURVEY §4/§6). This module assembles that layer
(:mod:`deepspeed_tpu.ops.transformer`) into an engine-ready masked-LM (+
optional NSP) pre-training model: ``init`` → param pytree, ``loss_fn(params,
batch, rng)`` → scalar, so ``deepspeed_tpu.initialize`` drives it like any
other model, composing with ZeRO/offload/precision untouched.

Batch schema (BingBertSquad-style pre-training):
    input_ids      [B, T] int32
    attention_mask [B, T] int32 (1 = live)           optional
    token_type_ids [B, T] int32                      optional
    mlm_labels     [B, T] int32, -100 = not masked   (MLM loss)
    nsp_labels     [B] int32 in {0, 1}               optional (NSP loss)
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.int8_training import (lm_logits,
                                              switchback_matmul)
from deepspeed_tpu.utils.jit import instance_cached_jit
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer,
                                           layer_norm_fp32)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    pre_layer_norm: bool = True      # reference default (preln modeling)
    with_nsp: bool = True
    dtype: Any = jnp.bfloat16
    # SwitchBack int8 projections in every encoder layer + the MLM
    # dense/unembedding GEMMs (see ops/int8_training.py; the tiny NSP
    # head stays full precision)
    int8_training: bool = False


PRESETS: Dict[str, dict] = {
    "bert-base": dict(hidden_size=768, num_hidden_layers=12,
                      num_attention_heads=12, intermediate_size=3072),
    "bert-large": dict(hidden_size=1024, num_hidden_layers=24,
                       num_attention_heads=16, intermediate_size=4096),
}


def config_for(name: str, **overrides) -> BertConfig:
    if name not in PRESETS:
        raise ValueError(f"unknown preset {name!r}: {sorted(PRESETS)}")
    return BertConfig(**{**PRESETS[name], **overrides})


class BertPreTrainingModel:
    """Engine-facing BERT MLM(+NSP) model over the fused training layer."""

    def __init__(self, config: BertConfig, train: bool = True):
        """``train=False`` disables dropout regardless of rng — the engine
        threads an rng into every loss call (including no-grad forward),
        so rng presence alone must not mean "apply dropout"."""
        self.config = config
        self.train = train
        layer_cfg = DeepSpeedTransformerConfig(
            hidden_size=config.hidden_size,
            intermediate_size=config.intermediate_size,
            heads=config.num_attention_heads,
            attn_dropout_ratio=config.attention_probs_dropout_prob,
            hidden_dropout_ratio=config.hidden_dropout_prob,
            num_hidden_layers=config.num_hidden_layers,
            initializer_range=config.initializer_range,
            layer_norm_eps=config.layer_norm_eps,
            pre_layer_norm=config.pre_layer_norm,
            fp16=config.dtype == jnp.bfloat16,
            int8_training=config.int8_training,
            training=True)
        self.layers = [DeepSpeedTransformerLayer(layer_cfg)
                       for _ in range(config.num_hidden_layers)]

    # -- init --------------------------------------------------------------
    def init(self, rng, **_) -> Dict[str, Any]:
        # one compiled executable, wrapper cached on the instance
        # (utils/jit.py): no per-tensor dispatch round trips at init
        return instance_cached_jit(self, self._build_params)(rng)

    def _build_params(self, rng) -> Dict[str, Any]:
        cfg = self.config
        E = cfg.hidden_size
        k = iter(jax.random.split(rng, 6 + cfg.num_hidden_layers))
        std = cfg.initializer_range
        dt = cfg.dtype

        def emb(key, shape):
            return (jax.random.normal(key, shape, jnp.float32) * std
                    ).astype(dt)

        params: Dict[str, Any] = {
            "wte": emb(next(k), (cfg.vocab_size, E)),
            "wpe": emb(next(k), (cfg.max_position_embeddings, E)),
            "wtte": emb(next(k), (cfg.type_vocab_size, E)),
            "emb_ln": {"scale": jnp.ones((E,), dt),
                       "bias": jnp.zeros((E,), dt)},
            "layers": [l.init(next(k)) for l in self.layers],
            # MLM head: dense + LN, unembedding tied to wte + output bias
            "mlm_dense": {"w": emb(next(k), (E, E)),
                          "b": jnp.zeros((E,), dt)},
            "mlm_ln": {"scale": jnp.ones((E,), dt),
                       "bias": jnp.zeros((E,), dt)},
            "mlm_bias": jnp.zeros((cfg.vocab_size,), jnp.float32),
        }
        if cfg.with_nsp:
            params["pooler"] = {"w": emb(next(k), (E, E)),
                                "b": jnp.zeros((E,), dt)}
            params["nsp"] = {"w": emb(jax.random.fold_in(rng, 99), (E, 2)),
                             "b": jnp.zeros((2,), jnp.float32)}
        return params

    # -- forward -----------------------------------------------------------
    def _ln(self, x, p):
        return layer_norm_fp32(x, p["scale"], p["bias"],
                               self.config.layer_norm_eps)

    def encode(self, params, input_ids, attention_mask=None,
               token_type_ids=None, rng=None, deterministic=True):
        cfg = self.config
        B, T = input_ids.shape
        tt = (token_type_ids if token_type_ids is not None
              else jnp.zeros_like(input_ids))
        x = (params["wte"][input_ids] +
             params["wpe"][jnp.arange(T)][None] +
             params["wtte"][tt]).astype(cfg.dtype)
        x = self._ln(x, params["emb_ln"])
        for layer, lp in zip(self.layers, params["layers"]):
            if rng is not None:
                rng = jax.random.fold_in(rng, 1)
            x = layer.apply(lp, x, attention_mask=attention_mask, rng=rng,
                            deterministic=deterministic)
        return x

    # -- losses ------------------------------------------------------------
    def loss_fn(self, params, batch, rng=None):
        cfg = self.config
        x = self.encode(params, batch["input_ids"],
                        batch.get("attention_mask"),
                        batch.get("token_type_ids"), rng=rng,
                        deterministic=(not self.train) or rng is None)
        # MLM head over masked positions
        int8 = self.config.int8_training
        if int8:
            h = switchback_matmul(x, params["mlm_dense"]["w"]) \
                + params["mlm_dense"]["b"]
        else:
            h = x @ params["mlm_dense"]["w"] + params["mlm_dense"]["b"]
        h = jax.nn.gelu(h.astype(jnp.float32),
                        approximate=False).astype(x.dtype)
        h = self._ln(h, params["mlm_ln"])
        logits = lm_logits(h, params["wte"].astype(h.dtype),
                           int8).astype(jnp.float32) + params["mlm_bias"]
        labels = batch["mlm_labels"]
        live = labels != -100
        safe = jnp.where(live, labels, 0)
        # lse - gold (not log_softmax): reductions only, no fp32 [.., V]
        # log-prob tensor materialized (see models/gpt2.py loss_fn)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, safe[..., None], -1)[..., 0]
        tok_ll = gold - lse
        denom = jnp.maximum(jnp.sum(live), 1)
        loss = -jnp.sum(jnp.where(live, tok_ll, 0.0)) / denom
        if cfg.with_nsp and "nsp_labels" in batch:
            pooled = jnp.tanh(x[:, 0] @ params["pooler"]["w"] +
                              params["pooler"]["b"])
            nsp_logits = (pooled @ params["nsp"]["w"].astype(pooled.dtype)
                          ).astype(jnp.float32) + params["nsp"]["b"]
            nsp_lp = jax.nn.log_softmax(nsp_logits, -1)
            nsp_ll = jnp.take_along_axis(
                nsp_lp, batch["nsp_labels"][:, None], -1)[:, 0]
            loss = loss - jnp.mean(nsp_ll)
        return loss

    def flops_per_token(self) -> float:
        """6N per token (training fwd+bwd), N = encoder+head params."""
        cfg = self.config
        E, F, L = cfg.hidden_size, cfg.intermediate_size, \
            cfg.num_hidden_layers
        per_layer = 4 * E * E + 2 * E * F
        n = L * per_layer + cfg.vocab_size * E
        return 6.0 * n

    # -- TP ----------------------------------------------------------------
    def tp_specs(self):
        """Megatron column/row-parallel PartitionSpecs for the engine's
        sharding policy: QKV + FFN-in column-parallel over 'tensor',
        attn-out + FFN-out row-parallel; embeddings/norms replicated."""
        from jax.sharding import PartitionSpec as P

        def layer_spec():
            return {
                "attn_qkvw": P(None, "tensor"), "attn_qkvb": P("tensor"),
                "attn_ow": P("tensor", None), "attn_ob": P(),
                "attn_nw": P(), "attn_nb": P(),
                "inter_w": P(None, "tensor"), "inter_b": P("tensor"),
                "output_w": P("tensor", None), "output_b": P(),
                "norm_w": P(), "norm_b": P(),
            }
        specs = {
            "wte": P(), "wpe": P(), "wtte": P(),
            "emb_ln": {"scale": P(), "bias": P()},
            "layers": [layer_spec() for _ in self.layers],
            "mlm_dense": {"w": P(), "b": P()},
            "mlm_ln": {"scale": P(), "bias": P()},
            "mlm_bias": P(),
        }
        if self.config.with_nsp:
            specs["pooler"] = {"w": P(), "b": P()}
            specs["nsp"] = {"w": P(), "b": P()}
        return specs
