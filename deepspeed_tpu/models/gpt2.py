"""GPT-2 model family (flax) — the flagship training model.

The reference trains GPT-2/Megatron-GPT through user-supplied torch modules
plus DeepSpeed's fused transformer kernel
(``csrc/transformer/ds_transformer_cuda.cpp``, wrapper
``deepspeed/ops/transformer/transformer.py:459``). Here the transformer block
is a flax module designed for the MXU: bf16 matmuls, fused-by-XLA
bias/gelu/layernorm epilogues, optional Pallas flash attention
(deepspeed_tpu.ops.flash_attention), ``jax.checkpoint`` for activation
rematerialization (analog of runtime/activation_checkpointing), and
Megatron-style tensor-parallel sharding expressed as PartitionSpecs
(``tp_specs``) instead of module surgery (module_inject/replace_module.py).

Sizes follow the GPT-2/GPT-3 ladder used by the reference benchmarks
(BASELINE.json configs: 125M…1.3B).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.mesh import DATA_AXES  # noqa: F401
from deepspeed_tpu.comm.mesh import seq_axis_active as _seq_axis_active
from deepspeed_tpu.ops.int8_training import (lm_logits,
                                              maybe_switchback)
from deepspeed_tpu.utils.jit import instance_cached_jit
from deepspeed_tpu.utils.sharding import maybe_constrain as _maybe_constrain


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    use_flash_attention: bool = True
    # flash tile-size override (0 = kernel default 256): the long-context
    # block-size A/B knob — bench --flash-block N
    flash_block: int = 0
    # sequence/context parallelism over the seq mesh axis (capability
    # beyond the reference — SURVEY §5.7); requires dropout == 0 in the
    # attention core. sp_mode: "ring" (ppermute K/V ring, O(T/sp) memory)
    # or "ulysses" (all-to-all head scatter, needs n_head % sp == 0)
    sequence_parallel: bool = False
    sp_mode: str = "ring"
    # pad vocab to a multiple of 128 (lane width) for MXU efficiency;
    # Megatron does the same for TP divisibility.
    vocab_pad_multiple: int = 128
    # ZeRO-3 offload_param cooperation: params live in TPU-host memory
    # (engine places them; stage3.py:448) and every block fetches its own
    # weights into HBM *inside* its remat region — backward re-fetches, so
    # HBM holds only a few layers of weights at a time.
    offload_params: bool = False
    # MoE FFN (reference Megatron-MoE training recipe: deepspeed/moe/layer
    # dropped into the FFN slot). num_experts > 0 turns the layers in
    # ``moe_layers`` (None → every OTHER layer starting at 1, the
    # Megatron-Deepspeed expert_interval=2 default) into expert-parallel
    # MoE blocks; experts shard over the data/fsdp axes via MoE.tp_specs.
    # The model's ``__call__``/``loss_fn`` fold the gate load-balancing
    # loss in with weight ``moe_aux_weight``.
    num_experts: int = 0
    moe_layers: Optional[tuple] = None
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # SwitchBack int8 training (ops/int8_training.py): the projection
    # GEMMs (fwd + dx) run int8 x int8 on the MXU at twice the bf16 rate;
    # dw stays full precision. Experimental, opt-in; composes with
    # ZeRO/offload unchanged (params stay bf16).
    int8_training: bool = False

    def __post_init__(self):
        if self.sp_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_mode must be 'ring' or 'ulysses', got "
                f"{self.sp_mode!r}")
        if self.num_experts > 0 and self.offload_params:
            raise ValueError(
                "num_experts > 0 with offload_params is unsupported: the "
                "in-step fetch table shares one block structure across "
                "layers, and MoE layers have a different param tree than "
                "dense ones")
        if self.num_experts > 0:
            layers = self.moe_layer_set
            if not layers:
                raise ValueError(
                    "num_experts > 0 needs at least one MoE layer "
                    "(moe_layers is empty)")
            bad = sorted(i for i in layers if not 0 <= i < self.n_layer)
            if bad:
                raise ValueError(
                    f"moe_layers {bad} out of range for n_layer="
                    f"{self.n_layer}")

    @property
    def moe_layer_set(self) -> frozenset:
        if self.num_experts <= 0:
            return frozenset()
        if self.moe_layers is not None:
            return frozenset(self.moe_layers)
        return frozenset(range(1, self.n_layer, 2))

    @property
    def padded_vocab_size(self) -> int:
        m = self.vocab_pad_multiple
        return ((self.vocab_size + m - 1) // m) * m


PRESETS: Dict[str, dict] = {
    "gpt2-125m": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-350m": dict(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-760m": dict(n_embd=1536, n_layer=24, n_head=16),
    "gpt2-1.3b": dict(n_embd=2048, n_layer=24, n_head=16),
    "gpt2-2.7b": dict(n_embd=2560, n_layer=32, n_head=32),
    "gpt2-6.7b": dict(n_embd=4096, n_layer=32, n_head=32),
}


def config_for(name: str, **overrides) -> GPT2Config:
    if name not in PRESETS:
        raise ValueError(f"unknown preset {name!r}: {sorted(PRESETS)}")
    return GPT2Config(**{**PRESETS[name], **overrides})


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        B, T, C = x.shape
        H = cfg.n_head
        qkv = nn.Dense(3 * C, dtype=cfg.dtype, name="c_attn",
                       dot_general=maybe_switchback(cfg.int8_training))(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, C // H)
        k = k.reshape(B, T, H, C // H)
        v = v.reshape(B, T, H, C // H)

        if cfg.sequence_parallel and _seq_axis_active():
            from deepspeed_tpu.comm.mesh import get_global_mesh
            if cfg.sp_mode == "ulysses":
                # all-to-all SP (DeepSpeed-Ulysses): full-seq attention
                # over head subsets; needs n_head % sp == 0
                from deepspeed_tpu.ops.ulysses_attention import (
                    ulysses_self_attention)
                y = ulysses_self_attention(q, k, v, get_global_mesh(),
                                           block=cfg.flash_block)
            else:
                from deepspeed_tpu.ops.ring_attention import (
                    ring_self_attention)
                y = ring_self_attention(q, k, v, get_global_mesh())
        elif cfg.use_flash_attention:
            from deepspeed_tpu.ops.attention import causal_attention
            y = causal_attention(q, k, v, block_q=cfg.flash_block,
                                 block_k=cfg.flash_block)
        else:
            scale = 1.0 / jnp.sqrt(C // H).astype(cfg.dtype)
            att = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            mask = jnp.tril(jnp.ones((T, T), bool))
            att = jnp.where(mask[None, None], att, jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            if cfg.dropout > 0.0 and not deterministic:
                att = nn.Dropout(cfg.dropout)(att, deterministic=False)
            y = jnp.einsum("bhqk,bkhd->bqhd", att, v)
        y = y.reshape(B, T, C)
        y = nn.Dense(C, dtype=cfg.dtype, name="c_proj",
                     dot_general=maybe_switchback(cfg.int8_training))(y)
        if cfg.dropout > 0.0 and not deterministic:
            y = nn.Dropout(cfg.dropout)(y, deterministic=False)
        return y


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        C = x.shape[-1]
        h = nn.Dense(4 * C, dtype=cfg.dtype, name="c_fc",
                     dot_general=maybe_switchback(cfg.int8_training))(x)
        h = jax.nn.gelu(h, approximate=True)
        h = nn.Dense(C, dtype=cfg.dtype, name="c_proj",
                     dot_general=maybe_switchback(cfg.int8_training))(h)
        if cfg.dropout > 0.0 and not deterministic:
            h = nn.Dropout(cfg.dropout)(h, deterministic=False)
        return h


class Block(nn.Module):
    """Transformer block. With ``moe=True`` the FFN slot holds an
    expert-parallel MoE (reference deepspeed/moe/layer.py inside a
    Megatron-MoE GPT layer) and ``__call__`` returns ``(x, l_aux)`` — the
    gate's load-balancing loss rides out as a scalar so remat never needs
    a mutable collection. One class for both so the LN/attention/residual
    structure cannot drift between dense and MoE models."""
    config: GPT2Config
    moe: bool = False

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        # LayerNorm in fp32 for stability, output cast back (the reference's
        # fused kernels keep LN accumulation in fp32 too: normalize_kernels.cu)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_1")(x)
        x = x + CausalSelfAttention(cfg, name="attn")(h, deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_2")(x)
        if self.moe:
            from deepspeed_tpu.moe.layer import MoE
            B, T, C = x.shape
            # tokens flatten to one group; the expert dispatch reshard
            # over the EP axes (= data/fsdp) IS the all-to-all
            y, l_aux, _ = MoE(hidden_size=C, num_experts=cfg.num_experts,
                              k=cfg.moe_top_k,
                              capacity_factor=cfg.moe_capacity_factor,
                              eval_capacity_factor=cfg.moe_capacity_factor,
                              min_capacity=4, dtype=cfg.dtype,
                              int8_training=cfg.int8_training,
                              name="moe")(h.reshape(B * T, C),
                                          train=not deterministic)
            return x + y.reshape(B, T, C), l_aux
        x = x + MLP(cfg, name="mlp")(h, deterministic)
        return x


def _fetch_to_device(tree, role: str, table: Optional[Dict[str, Any]]):
    """Host-memory param subtree → HBM (offload_param in-step fetch).

    ``table`` is the owning :class:`GPT2LMModel`'s fetch table (instance
    state, filled in by the engine via ``set_param_fetch_shardings`` —
    role → NamedSharding subtree with memory_kind='device'). Explicit
    NamedShardings are required under SPMD: a bare memory-space transfer
    leaves the partitioner's placement annotation unsharded and it rejects
    the program. Identity when no engine installed shardings (standalone
    use, eager-staging engines, non-TPU backends) and for concrete
    (non-traced) values: the fetch only makes sense inside the compiled
    step — during eager ``model.init`` a device_put would commit fresh
    params to one device."""
    if table is None or not table.get("active", False):
        return tree
    sh = table.get(role)
    if sh is None:
        return tree

    def put(x, s):
        if not isinstance(x, jax.core.Tracer):
            return x
        return jax.device_put(x, s)

    # flax hands the block subtree in as a FrozenDict while the engine's
    # sharding subtree is a plain dict — isomorphic but not tree_map
    # compatible. Both flatten in sorted-key order, so zip by leaf.
    leaves, treedef = jax.tree.flatten(tree)
    sh_leaves = jax.tree.leaves(sh)
    if len(sh_leaves) != len(leaves):
        raise ValueError(
            f"offload_param fetch shardings for role {role!r} have "
            f"{len(sh_leaves)} leaves, params have {len(leaves)}")
    return jax.tree.unflatten(
        treedef, [put(x, s) for x, s in zip(leaves, sh_leaves)])


class GPT2(nn.Module):
    """Causal LM. ``__call__`` returns logits; ``loss`` the mean CE loss."""
    config: GPT2Config
    # offload_param fetch table owned by the GPT2LMModel wrapper (mutable
    # dict shared by reference; per-model so two engines cannot clobber
    # each other's placements)
    fetch_table: Optional[Dict[str, Any]] = None

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True):
        cfg = self.config
        B, T = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.padded_vocab_size, cfg.n_embd), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.n_positions, cfg.n_embd), jnp.float32)
        if cfg.offload_params:
            wte = _fetch_to_device(wte, "wte", self.fetch_table)
            wpe = _fetch_to_device(wpe, "wpe", self.fetch_table)
        # gather rows THEN cast (16 MB vs casting the whole fp32 table to
        # a 100+ MB bf16 copy per step), and slice positions statically
        x = wte[input_ids].astype(cfg.dtype) + \
            wpe[:T].astype(cfg.dtype)[None]
        x = _maybe_constrain(x, P(DATA_AXES, "seq", None))
        if cfg.dropout > 0.0 and not deterministic:
            x = nn.Dropout(cfg.dropout)(x, deterministic=False)

        block = Block
        if cfg.offload_params:
            # the fetch sits INSIDE the remat region below, so backward
            # re-fetches this block's weights instead of pinning them in
            # HBM across the whole fwd+bwd (coordinator-prefetch analog —
            # XLA's scheduler overlaps the DMA with neighbouring compute)
            block = nn.map_variables(
                block, "params",
                trans_in_fn=lambda t: _fetch_to_device(
                    t, "block", self.fetch_table),
                trans_out_fn=lambda t: t, mutable=True, init=True)
        moe_set = cfg.moe_layer_set
        if cfg.remat:
            # dots-saveable + the flash kernel's tagged output: the policy
            # cannot see through the kernel's custom_vjp, so without the
            # name the flash forward re-runs in backward (ops/attention.py)
            policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_attn_out"))
            # deterministic is control flow (dropout gate, MoE train-mode
            # capacity), not data — keep it static under the remat trace
            # (argnum 2: flax counts the module instance as 0)
            block = nn.remat(block, prevent_cse=False, policy=policy,
                             static_argnums=(2,))
        l_aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layer):
            if i in moe_set:
                x, l_aux = block(cfg, moe=True,
                                 name=f"h_{i}")(x, deterministic)
                l_aux_total = l_aux_total + l_aux.astype(jnp.float32)
            else:
                x = block(cfg, name=f"h_{i}")(x, deterministic)

        ln_f = nn.LayerNorm
        if cfg.offload_params:
            ln_f = nn.map_variables(
                ln_f, "params",
                trans_in_fn=lambda t: _fetch_to_device(
                    t, "ln_f", self.fetch_table),
                trans_out_fn=lambda t: t, mutable=True, init=True)
        x = ln_f(dtype=cfg.dtype, name="ln_f")(x)
        logits = lm_logits(x, wte.astype(cfg.dtype), cfg.int8_training)
        if moe_set:
            return logits, l_aux_total
        return logits


class GPT2LMModel:
    """Engine-facing wrapper: init + loss_fn + tp_specs.

    ``loss_fn(params, batch, rng)`` — batch is ``{"input_ids": [B,T] int32}``
    (next-token prediction) or ``{"input_ids", "labels"}``.
    """

    def __init__(self, config: GPT2Config):
        self.config = config
        self._fetch_table: Dict[str, Any] = {"active": False}
        self.module = GPT2(config, fetch_table=self._fetch_table)

    @property
    def handles_param_offload(self) -> bool:
        """Engine hint: with ``offload_params`` the model performs its own
        per-layer HBM fetches, so the engine must not coarse-fetch the
        whole tree at step start."""
        return self.config.offload_params

    def set_param_fetch_shardings(self, device_shardings) -> None:
        """Engine-provided device placements for the in-step fetches (the
        ZeRO policy's param shardings with memory_kind='device'). All
        blocks share one structure, so h_0's subtree serves every layer.
        ``None`` deactivates the in-jit fetches (engine stages eagerly)."""
        if device_shardings is None:
            self._fetch_table["active"] = False
            return
        self._fetch_table["active"] = True
        self._fetch_table["wte"] = device_shardings["wte"]
        self._fetch_table["wpe"] = device_shardings["wpe"]
        self._fetch_table["ln_f"] = device_shardings["ln_f"]
        if "h_0" in device_shardings:
            self._fetch_table["block"] = device_shardings["h_0"]

    def init(self, rng, example_batch=None, batch_size: int = 2,
             seq_len: Optional[int] = None):
        seq_len = seq_len or min(self.config.n_positions, 128)
        if example_batch is not None:
            ids = example_batch["input_ids"]
        else:
            ids = jnp.zeros((batch_size, seq_len), jnp.int32)
        # offload fetches are step-time only; flax jits init internally,
        # so without this guard the fetch would commit fresh params to one
        # device before the engine shards them
        prev = self._fetch_table.get("active", False)
        self._fetch_table["active"] = False
        try:
            # one compiled executable, wrapper cached on the instance:
            # params materialize device-side in a single execution
            # instead of per-op dispatch round trips (utils/jit.py)
            variables = instance_cached_jit(self, self.module.init)(
                rng, ids)
        finally:
            self._fetch_table["active"] = prev
        return variables["params"]

    def apply(self, params, input_ids, deterministic=True, rngs=None):
        """Returns logits; with MoE layers, ``(logits, l_aux_total)``."""
        return self.module.apply({"params": params}, input_ids,
                                 deterministic=deterministic, rngs=rngs)

    def loss_fn(self, params, batch, rng=None):
        cfg = self.config
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        rngs = {}
        if rng is not None and cfg.dropout > 0.0:
            rngs["dropout"] = rng
        if rng is not None and cfg.num_experts > 0:
            # gate randomness (rts noise / top-2 second-expert sampling)
            rngs["gating"] = jax.random.fold_in(rng, 1)
        rngs = rngs or None
        out = self.apply(params, input_ids,
                         deterministic=rng is None, rngs=rngs)
        l_aux = None
        if cfg.num_experts > 0:
            logits, l_aux = out
        else:
            logits = out
        if labels is None:
            labels = input_ids[:, 1:]
            logits = logits[:, :-1]
        logits = logits.astype(jnp.float32)
        # lse - gold instead of log_softmax: avoids materializing a full
        # fp32 [B, T, V] log-prob tensor (reductions only — at 350m/seq
        # 1024 that tensor is ~0.8 GB of HBM write+read per step)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None],
                                   axis=-1)[..., 0]
        nll = lse - gold
        mask = (labels >= 0) & (labels < self.config.vocab_size)
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
        if l_aux is not None:
            loss = loss + cfg.moe_aux_weight * l_aux
        return loss

    def tp_specs(self):
        """Megatron-style tensor-parallel placement: attention qkv + mlp up
        are column-parallel, the projections row-parallel, embeddings
        vocab-parallel (module_inject/layers.py:9-61 semantics, as sharding
        specs instead of module replacement)."""
        cfg = self.config
        block = {
            "ln_1": {"scale": P(), "bias": P()},
            "ln_2": {"scale": P(), "bias": P()},
            "attn": {
                "c_attn": {"kernel": P(None, "tensor"), "bias": P("tensor")},
                "c_proj": {"kernel": P("tensor", None), "bias": P()},
            },
            "mlp": {
                "c_fc": {"kernel": P(None, "tensor"), "bias": P("tensor")},
                "c_proj": {"kernel": P("tensor", None), "bias": P()},
            },
        }
        specs = {"wte": P("tensor", None), "wpe": P(),
                 "ln_f": {"scale": P(), "bias": P()}}
        moe_set = cfg.moe_layer_set
        if moe_set:
            from deepspeed_tpu.moe.layer import MoE
            moe_block = dict(block)
            del moe_block["mlp"]
            moe_block["moe"] = MoE.tp_specs()
        for i in range(cfg.n_layer):
            specs[f"h_{i}"] = moe_block if i in moe_set else block
        return specs

    def param_count(self, params) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))

    def flops_per_token(self) -> float:
        """~6 * N_active_params per token (training fwd+bwd). MoE layers
        count attention + top_k expert FFNs — the ACTIVE compute, not the
        parameter count (standard MoE throughput accounting)."""
        cfg = self.config
        n_moe = len(cfg.moe_layer_set)
        dense_ffn = 8 * cfg.n_embd ** 2
        n = (cfg.padded_vocab_size * cfg.n_embd
             + cfg.n_positions * cfg.n_embd
             + cfg.n_layer * (4 * cfg.n_embd ** 2)            # attention
             + (cfg.n_layer - n_moe) * dense_ffn              # dense FFN
             + n_moe * cfg.moe_top_k * dense_ffn)             # active experts
        return 6.0 * n
