"""Flops profiler.

Analog of ``deepspeed/profiling/flops_profiler/profiler.py`` (1,226 LoC of
torch monkey-patching to count MACs per module). On TPU the compiler
already knows: XLA's cost analysis reports exact flops/bytes for the
*optimized* computation, so the profiler asks the compiled executable
instead of shimming every op — more accurate (post-fusion) and zero
overhead in the hot path.

``get_model_profile(fn, args)`` mirrors the reference's standalone API;
:class:`FlopsProfiler` mirrors the engine-integrated start/stop/print flow
(``runtime/engine.py:1779-1798``).

Per-module attribution (the reference's module tree, its
``print_model_profile`` aggregated-depth view): where torch hooks every
``nn.Module``, the TPU-native source of truth is the jaxpr — flax wraps
every module call in ``jax.named_scope``, so each equation carries its
module path (``GPT2/h_3/attn/c_attn``). :func:`module_flops_breakdown`
walks the jaxpr (recursing through pjit/remat/scan/cond, scaling scan
bodies by trip count) counting analytic FLOPs per equation and groups
them by name-stack prefix. The per-module numbers sum exactly to the
walk's aggregate by construction; XLA's post-fusion executable count is
reported alongside (fusion/remat make it differ — both are printed).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np


def _params_count(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree)
               if hasattr(x, "shape"))


# ------------------------------------------------------- jaxpr walking
# Analytic per-equation FLOP estimates. Matmuls/convs carry ~all model
# FLOPs (the reference's profiler counts the same way: MACs of
# Linear/conv modules + elementwise, flops_profiler/profiler.py); memory
# movement (reshape/slice/broadcast/gather) counts 0.

_ELEMENTWISE = {
    "add", "sub", "mul", "div", "pow", "max", "min", "rem", "neg", "abs",
    "exp", "log", "log1p", "expm1", "tanh", "sqrt", "rsqrt", "logistic",
    "erf", "erfc", "erf_inv", "sign", "floor", "ceil", "round", "cos",
    "sin", "tan", "atan2", "integer_pow", "select_n", "clamp", "nextafter",
    "and", "or", "xor", "not", "eq", "ne", "ge", "gt", "le", "lt",
    "is_finite", "add_any", "square",
}
_REDUCTIONS = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
               "reduce_and", "reduce_or", "argmax", "argmin",
               "cumsum", "cumprod", "cummax", "cummin", "reduce_precision"}
_CALL_PRIMS = {"pjit", "closed_call", "core_call", "xla_call", "remat2",
               "remat", "custom_jvp_call", "custom_vjp_call",
               "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
               "checkpoint", "named_call", "custom_vjp_call_fwd"}


def _aval_size(v) -> int:
    try:
        return int(np.prod(v.aval.shape))
    except Exception:  # noqa: BLE001 — abstract tokens etc.
        return 0


def _eqn_flops(eqn) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        (lc, _), _ = eqn.params["dimension_numbers"]
        k = 1
        for d in lc:
            k *= eqn.invars[0].aval.shape[d]
        return 2.0 * _aval_size(eqn.outvars[0]) * k
    if name == "conv_general_dilated":
        rhs = eqn.invars[1].aval.shape
        dn = eqn.params["dimension_numbers"]
        out_feature = rhs[dn.rhs_spec[0]]
        per_out = (2.0 * int(np.prod(rhs)) / max(out_feature, 1))
        return per_out * _aval_size(eqn.outvars[0])
    if name in _ELEMENTWISE:
        return float(_aval_size(eqn.outvars[0]))
    if name in _REDUCTIONS:
        return float(_aval_size(eqn.invars[0]))
    return 0.0


def _inner_jaxprs(eqn):
    """(jaxpr, multiplier) pairs for call-like primitives. Scan bodies
    run ``length`` times; cond branches are counted at their max (an
    upper bound — the trace cannot know which branch runs)."""
    from jax._src.core import Jaxpr  # stable across recent jax

    def as_jaxpr(x):
        if isinstance(x, Jaxpr):
            return x
        if hasattr(x, "jaxpr"):
            return x.jaxpr
        return None

    name = eqn.primitive.name
    if name == "scan":
        body = as_jaxpr(eqn.params["jaxpr"])
        return [(body, float(eqn.params.get("length", 1)))]
    if name == "while":
        # body trip count is data-dependent; count one iteration
        return [(as_jaxpr(eqn.params["body_jaxpr"]), 1.0)]
    if name == "cond":
        branches = [as_jaxpr(b) for b in eqn.params["branches"]]
        totals = [(_jaxpr_flops_total(b), b) for b in branches if b]
        if not totals:
            return []
        return [(max(totals, key=lambda t: t[0])[1], 1.0)]
    if name in _CALL_PRIMS:
        out = []
        for v in eqn.params.values():
            for item in (v if isinstance(v, (list, tuple)) else [v]):
                j = as_jaxpr(item)
                if j is not None:
                    out.append((j, 1.0))
        return out
    return []


def _jaxpr_flops_total(jx) -> float:
    total = 0.0
    for eqn in jx.eqns:
        total += _eqn_flops(eqn)
        for inner, mult in _inner_jaxprs(eqn):
            total += mult * _jaxpr_flops_total(inner)
    return total


def _walk_modules(jx, prefix: str, mult: float, acc: Dict[str, float]):
    for eqn in jx.eqns:
        ns = str(eqn.source_info.name_stack)
        # inner name stacks are relative to the enclosing call site
        full = "/".join(s for s in (prefix, ns) if s)
        inner = _inner_jaxprs(eqn)
        if inner:
            for ij, m in inner:
                _walk_modules(ij, full, mult * m, acc)
        else:
            f = _eqn_flops(eqn)
            if f:
                acc[full] = acc.get(full, 0.0) + mult * f


def module_flops_breakdown(fn: Callable, *args, depth: Optional[int] = 2,
                           jaxpr=None, **kwargs) -> Dict[str, float]:
    """Per-module analytic FLOPs for one call of ``fn`` — the TPU-native
    analog of the reference profiler's per-module tree
    (``flops_profiler/profiler.py``, torch module hooks): flax's
    ``named_scope`` paths in the jaxpr are the module boundaries.

    ``depth`` collapses paths to their first N segments (``None`` keeps
    full paths). Values sum EXACTLY to the ``""``-keyed aggregate (ops
    outside any named module are keyed by their call-site path, at
    minimum the empty root). Pass ``jaxpr`` (a ClosedJaxpr, e.g. from
    ``jax.jit(fn).trace(...).jaxpr``) to reuse an existing trace."""
    if jaxpr is None:
        jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    acc: Dict[str, float] = {}
    _walk_modules(jaxpr.jaxpr, "", 1.0, acc)
    if depth is not None:
        collapsed: Dict[str, float] = {}
        for path, f in acc.items():
            key = "/".join(path.split("/")[:depth]) if path else ""
            collapsed[key] = collapsed.get(key, 0.0) + f
        acc = collapsed
    return acc


def _params_by_module(params, path: str):
    """Best-effort param count for a module path: strip the root module
    segment, then walk dict keys."""
    if params is None or not isinstance(params, dict):
        return None
    segs = path.split("/")
    if len(segs) < 2:  # root rows would claim the whole tree — show '-'
        return None
    node = params
    if "params" in node and isinstance(node["params"], dict):
        node = node["params"]
    for seg in segs[1:]:  # segs[0] is the root module's own name
        if isinstance(node, dict) and seg in node:
            node = node[seg]
        else:
            return None
    return _params_count(node)


def format_module_table(breakdown: Dict[str, float],
                        params: Any = None) -> str:
    """Reference-style per-module table: FLOPs, share of total, params.
    Total line is the exact sum of the rows above it."""
    total = sum(breakdown.values()) or 1.0
    rows = sorted(breakdown.items(), key=lambda kv: -kv[1])
    width = max([len(k) for k in breakdown] + [8])
    lines = [f"{'module':<{width}}  {'flops':>10}  {'%':>6}  {'params':>9}"]
    for path, f in rows:
        pcount = _params_by_module(params, path)
        lines.append(
            f"{path or '(root)':<{width}}  "
            f"{number_to_string(f):>10}  {100 * f / total:>5.1f}%  "
            f"{number_to_string(pcount) if pcount is not None else '-':>9}")
    lines.append(f"{'TOTAL':<{width}}  "
                 f"{number_to_string(sum(breakdown.values())):>10}  "
                 f"{'100.0%':>6}  "
                 f"{number_to_string(_params_count(params)) if params is not None else '-':>9}")
    return "\n".join(lines)


def number_to_string(num: float, units: Optional[str] = None,
                     precision: int = 2) -> str:
    """Human units like the reference's flops_to_string/params_to_string."""
    for threshold, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                              (1e3, "K")):
        if units == suffix or (units is None and abs(num) >= threshold):
            return f"{num / threshold:.{precision}f} {suffix}"
    return f"{num:.{precision}f}"


def get_model_profile(fn: Callable, args: Tuple = (), kwargs: Dict = None,
                      warm_up: int = 1, num_steps: int = 3,
                      as_string: bool = False,
                      params: Any = None,
                      per_module_depth: Optional[int] = 2) -> Dict[str, Any]:
    """Profile a jittable callable: flops, HBM bytes, params, latency,
    achieved FLOP/s (reference ``get_model_profile``), plus the
    per-module breakdown table (``per_module_depth=None`` disables;
    reference analog: the profiler's aggregated module tree)."""
    kwargs = kwargs or {}
    # ONE trace serves both the compiled cost analysis and the module
    # walk (jit(fn).trace exposes the jaxpr and lowers from it); older
    # jax without .trace falls back to the lower-only path
    closed = None
    try:
        traced = jax.jit(fn).trace(*args, **kwargs)
        closed = traced.jaxpr
        compiled = traced.lower().compile()
    except AttributeError:
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    # one executable-stats plumbing for the whole codebase
    # (telemetry/compile_watch.py) — the profiler and the compile watch
    # can never report different numbers for the same executable
    from deepspeed_tpu.telemetry.compile_watch import executable_cost
    cost = executable_cost(compiled)
    breakdown = None
    if per_module_depth is not None:
        # never let attribution break the aggregate profile (a custom
        # primitive whose params the jaxpr walker doesn't recognize, a
        # jax version drifting a param key) — omit the breakdown instead
        try:
            breakdown = module_flops_breakdown(
                fn, *args, depth=per_module_depth, jaxpr=closed, **kwargs)
        except Exception as e:  # noqa: BLE001
            from deepspeed_tpu.utils.logging import logger
            logger.warning(f"per-module breakdown failed: {e}")
            breakdown = None
    for _ in range(max(warm_up, 1)):
        out = compiled(*args, **kwargs)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(max(num_steps, 1)):
        out = compiled(*args, **kwargs)
    # force a host sync (block_until_ready alone can return early through
    # remote-device relays — see .claude/skills/verify/SKILL.md)
    np.asarray(jax.tree.leaves(out)[0])
    latency = (time.perf_counter() - t0) / max(num_steps, 1)

    prof = {
        "flops": cost["flops"],
        "bytes_accessed": cost["bytes_accessed"],
        "hbm_bytes": cost.get("hbm_bytes", 0.0),
        "params": _params_count(params if params is not None else args),
        "latency_s": latency,
        "flops_per_s": cost["flops"] / latency if latency > 0 else 0.0,
    }
    if breakdown is not None:
        prof["module_breakdown"] = breakdown
        prof["module_flops_total"] = sum(breakdown.values())
    if as_string:
        prof = {
            "flops": number_to_string(prof["flops"]) + "FLOPs",
            "bytes_accessed": number_to_string(prof["bytes_accessed"]) + "B",
            "hbm_bytes": number_to_string(prof["hbm_bytes"]) + "B",
            "params": number_to_string(prof["params"]),
            "latency_s": f"{latency * 1e3:.2f} ms",
            "flops_per_s": number_to_string(prof["flops_per_s"]) + "FLOPS",
        }
        if breakdown is not None:
            prof["module_table"] = format_module_table(
                breakdown, params if params is not None
                else (args[0] if args else None))
    return prof


class FlopsProfiler:
    """Engine-integrated profiler (config section ``flops_profiler``):
    records the step's cost analysis + wall time at ``profile_step`` and
    prints the reference-style summary."""

    def __init__(self, engine=None, profile_step: int = 1,
                 top_modules: int = 1, detailed: bool = True,
                 output_file: Optional[str] = None):
        self.engine = engine
        self.profile_step = profile_step
        self.detailed = detailed
        self.output_file = output_file
        self.started = False
        self._t0 = 0.0
        self.results: Dict[str, Any] = {}

    def start_profile(self) -> None:
        self.started = True
        self._latency = None
        self._t0 = time.perf_counter()

    def mark_step_done(self) -> None:
        """Call right after the host sync — freezes the latency BEFORE any
        cost-analysis work so compile/analysis time never pollutes it."""
        if self.started:
            self._latency = time.perf_counter() - self._t0

    def stop_profile(self, flops: float = 0.0, params: int = 0,
                     module_breakdown: Optional[Dict[str, float]] = None
                     ) -> None:
        if not self.started:
            return
        latency = (self._latency if self._latency is not None
                   else time.perf_counter() - self._t0)
        self.results = {
            "flops": flops, "params": params, "latency_s": latency,
            "flops_per_s": flops / latency if latency > 0 else 0.0}
        if module_breakdown:
            self.results["module_breakdown"] = module_breakdown
        self.started = False

    def print_model_profile(self) -> str:
        r = self.results
        lines = [
            "-" * 60,
            "DeepSpeed-TPU Flops Profiler",
            f"params:               {number_to_string(r.get('params', 0))}",
            f"fwd+bwd+step flops:   {number_to_string(r.get('flops', 0))}",
            f"step latency:         {r.get('latency_s', 0) * 1e3:.2f} ms",
            f"achieved:             "
            f"{number_to_string(r.get('flops_per_s', 0))}FLOPS",
            "-" * 60,
        ]
        if r.get("module_breakdown"):
            # the reference's aggregated module tree (forward
            # attribution; its bwd convention is 2x fwd)
            ptree = getattr(getattr(self.engine, "state", None),
                            "params", None)
            lines += ["per-module forward FLOPs:",
                      format_module_table(r["module_breakdown"], ptree),
                      "-" * 60]
        text = "\n".join(lines)
        if self.output_file:
            with open(self.output_file, "a") as f:
                f.write(text + "\n")
        else:
            print(text)
        return text
