"""Flops profiler.

Analog of ``deepspeed/profiling/flops_profiler/profiler.py`` (1,226 LoC of
torch monkey-patching to count MACs per module). On TPU the compiler
already knows: XLA's cost analysis reports exact flops/bytes for the
*optimized* computation, so the profiler asks the compiled executable
instead of shimming every op — more accurate (post-fusion) and zero
overhead in the hot path.

``get_model_profile(fn, args)`` mirrors the reference's standalone API;
:class:`FlopsProfiler` mirrors the engine-integrated start/stop/print flow
(``runtime/engine.py:1779-1798``).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np


def _params_count(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree)
               if hasattr(x, "shape"))


def _cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):   # older jax returns [dict]
        cost = cost[0] if cost else {}
    return {"flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            "compiled": compiled}


def number_to_string(num: float, units: Optional[str] = None,
                     precision: int = 2) -> str:
    """Human units like the reference's flops_to_string/params_to_string."""
    for threshold, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                              (1e3, "K")):
        if units == suffix or (units is None and abs(num) >= threshold):
            return f"{num / threshold:.{precision}f} {suffix}"
    return f"{num:.{precision}f}"


def get_model_profile(fn: Callable, args: Tuple = (), kwargs: Dict = None,
                      warm_up: int = 1, num_steps: int = 3,
                      as_string: bool = False,
                      params: Any = None) -> Dict[str, Any]:
    """Profile a jittable callable: flops, HBM bytes, params, latency,
    achieved FLOP/s (reference ``get_model_profile``)."""
    kwargs = kwargs or {}
    cost = _cost_analysis(fn, *args, **kwargs)
    compiled = cost.pop("compiled")
    for _ in range(max(warm_up, 1)):
        out = compiled(*args, **kwargs)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(max(num_steps, 1)):
        out = compiled(*args, **kwargs)
    # force a host sync (block_until_ready alone can return early through
    # remote-device relays — see .claude/skills/verify/SKILL.md)
    np.asarray(jax.tree.leaves(out)[0])
    latency = (time.perf_counter() - t0) / max(num_steps, 1)

    prof = {
        "flops": cost["flops"],
        "bytes_accessed": cost["bytes_accessed"],
        "params": _params_count(params if params is not None else args),
        "latency_s": latency,
        "flops_per_s": cost["flops"] / latency if latency > 0 else 0.0,
    }
    if as_string:
        prof = {
            "flops": number_to_string(prof["flops"]) + "FLOPs",
            "bytes_accessed": number_to_string(prof["bytes_accessed"]) + "B",
            "params": number_to_string(prof["params"]),
            "latency_s": f"{latency * 1e3:.2f} ms",
            "flops_per_s": number_to_string(prof["flops_per_s"]) + "FLOPS",
        }
    return prof


class FlopsProfiler:
    """Engine-integrated profiler (config section ``flops_profiler``):
    records the step's cost analysis + wall time at ``profile_step`` and
    prints the reference-style summary."""

    def __init__(self, engine=None, profile_step: int = 1,
                 top_modules: int = 1, detailed: bool = True,
                 output_file: Optional[str] = None):
        self.engine = engine
        self.profile_step = profile_step
        self.output_file = output_file
        self.started = False
        self._t0 = 0.0
        self.results: Dict[str, Any] = {}

    def start_profile(self) -> None:
        self.started = True
        self._latency = None
        self._t0 = time.perf_counter()

    def mark_step_done(self) -> None:
        """Call right after the host sync — freezes the latency BEFORE any
        cost-analysis work so compile/analysis time never pollutes it."""
        if self.started:
            self._latency = time.perf_counter() - self._t0

    def stop_profile(self, flops: float = 0.0, params: int = 0) -> None:
        if not self.started:
            return
        latency = (self._latency if self._latency is not None
                   else time.perf_counter() - self._t0)
        self.results = {
            "flops": flops, "params": params, "latency_s": latency,
            "flops_per_s": flops / latency if latency > 0 else 0.0}
        self.started = False

    def print_model_profile(self) -> str:
        r = self.results
        lines = [
            "-" * 60,
            "DeepSpeed-TPU Flops Profiler",
            f"params:               {number_to_string(r.get('params', 0))}",
            f"fwd+bwd+step flops:   {number_to_string(r.get('flops', 0))}",
            f"step latency:         {r.get('latency_s', 0) * 1e3:.2f} ms",
            f"achieved:             "
            f"{number_to_string(r.get('flops_per_s', 0))}FLOPS",
            "-" * 60,
        ]
        text = "\n".join(lines)
        if self.output_file:
            with open(self.output_file, "a") as f:
                f.write(text + "\n")
        else:
            print(text)
        return text
