"""Profiler traces + named ranges.

Analog of the reference's NVTX instrumentation + nsight workflow
(``deepspeed/utils/nvtx.py`` ``instrument_w_nvtx``; SURVEY §5.1 maps it
to "jax profiler traces + xplane, per-phase named scopes"):

* ``instrument``: decorator wrapping a function in ``jax.named_scope``
  (shows up in xplane/Perfetto exactly where nvtx ranges show in
  nsight) plus an optional ``jax.profiler.TraceAnnotation`` for
  host-side spans.
* ``trace(logdir)``: context manager around
  ``jax.profiler.start_trace/stop_trace`` — the ``nsys profile``
  one-liner equivalent; view with TensorBoard's profile plugin or
  Perfetto.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Callable, Optional

import jax


def instrument(fn: Optional[Callable] = None, *, name: Optional[str] = None):
    """``@instrument`` or ``@instrument(name="phase")`` — the
    ``instrument_w_nvtx`` analog."""
    def deco(f):
        scope = name or getattr(f, "__qualname__", f.__name__)

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with jax.named_scope(scope), \
                    jax.profiler.TraceAnnotation(scope):
                return f(*args, **kwargs)
        return wrapper

    return deco(fn) if fn is not None else deco


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Capture an xplane trace for everything inside the block."""
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Host+device range annotation (``with annotate("fwd"): ...``)."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield
