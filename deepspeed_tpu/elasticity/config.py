"""Elasticity config (analog of ``deepspeed/elasticity/config.py``).

Keys keep the reference spelling (``min_gpus``/``max_gpus`` etc.) so elastic
config json ports unchanged; on TPU a "gpu" is a chip and
``num_gpus_per_node`` is chips-per-host (e.g. 4 on v5e hosts).
"""
from __future__ import annotations


class ElasticityError(Exception):
    """Base exception for elasticity errors."""


class ElasticityConfigError(ElasticityError):
    """Elasticity configuration error."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size incompatible with the elastic config."""


LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityConfig:
    """Constructed from the ``elasticity`` section of the DS config:

    {"enabled": true, "max_train_batch_size": 2000,
     "micro_batch_sizes": [2,4,6], "min_gpus": 1, "max_gpus": 10000,
     "min_time": 20, "version": 0.2, "num_gpus_per_node": 4,
     "model_parallel_size": 1}
    """

    def __init__(self, param_dict: dict):
        self.enabled = param_dict.get("enabled", False)
        if not self.enabled:
            return
        try:
            self.max_acceptable_batch_size = param_dict[
                "max_train_batch_size"]
            self.micro_batches = param_dict["micro_batch_sizes"]
        except KeyError as e:
            raise ElasticityConfigError(
                f"missing required elasticity key: {e}") from e
        if not isinstance(self.micro_batches, list) or \
                not self.micro_batches:
            raise ElasticityConfigError(
                "micro_batch_sizes must be a non-empty list")
        if any((not isinstance(m, int)) or m <= 0
               for m in self.micro_batches):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive ints, got "
                f"{self.micro_batches}")
        self.min_gpus = param_dict.get("min_gpus", 1)
        self.max_gpus = param_dict.get("max_gpus", -1)
        if self.min_gpus < 1 or self.max_gpus == 0 or \
                (self.max_gpus != -1 and self.max_gpus < self.min_gpus):
            raise ElasticityConfigError(
                f"invalid min_gpus={self.min_gpus} max_gpus={self.max_gpus}")
        self.model_parallel_size = param_dict.get("model_parallel_size", 1)
        self.num_gpus_per_node = param_dict.get("num_gpus_per_node", 1)
        self.min_time = param_dict.get("min_time", 0)
        self.version = float(param_dict.get("version", 0.1))
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch",
                                                       True)
        self.ignore_non_elastic_batch_info = param_dict.get(
            "ignore_non_elastic_batch_info", False)

    def repr(self):
        return self.__dict__
