"""Elastic training: batch-size-compatible world-size math.

Analog of ``deepspeed/elasticity/`` — the v0.1/v0.2 algorithms port as pure
arithmetic; the torch-elastic ``DSElasticAgent`` has no TPU analog (slice
membership is fixed per job), so recovery is re-mesh + universal-checkpoint
restore (deepspeed_tpu.checkpoint).
"""
from deepspeed_tpu.elasticity.config import (ElasticityConfig,
                                             ElasticityConfigError,
                                             ElasticityError,
                                             ElasticityIncompatibleWorldSize)
from deepspeed_tpu.elasticity.elasticity import (compute_elastic_config,
                                                 elasticity_enabled,
                                                 ensure_immutable_elastic_config,
                                                 get_candidate_batch_sizes,
                                                 get_valid_gpus)

__all__ = ["ElasticityConfig", "ElasticityConfigError", "ElasticityError",
           "ElasticityIncompatibleWorldSize", "compute_elastic_config",
           "elasticity_enabled", "ensure_immutable_elastic_config",
           "get_candidate_batch_sizes", "get_valid_gpus"]
