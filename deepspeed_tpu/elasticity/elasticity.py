"""Elastic batch/world-size math.

Analog of ``deepspeed/elasticity/elasticity.py`` (v0.1 ``:125``, v0.2
``:173``, ``compute_elastic_config`` ``:287``): choose one global batch size
whose (micro_batch × grad-accumulation × world) factorisations cover the
largest set of chip counts, so a job can scale up/down across that set with
bit-identical convergence behavior. Pure arithmetic — ports as math, not
code; on TPU "gpus" are chips and v0.2's node granularity is
host granularity (chips-per-host).
"""
from __future__ import annotations

import math
from functools import reduce
from typing import List, Optional, Tuple

from deepspeed_tpu.elasticity.config import (ElasticityConfig,
                                             ElasticityConfigError,
                                             ElasticityError,
                                             ElasticityIncompatibleWorldSize,
                                             LATEST_ELASTICITY_VERSION)

# highly composite numbers — dense divisor sets make good batch multipliers
HCN_LIST = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
            1260, 1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720,
            45360, 50400]


def _lcm(nums: List[int]) -> int:
    return reduce(lambda a, b: a * b // math.gcd(a, b), nums)


def get_candidate_batch_sizes(base_list: List[int],
                              max_batch: int) -> List[int]:
    """For each base, scale by the largest HCN that keeps base*hcn ≤ max."""
    out = set()
    for base in base_list:
        if base >= max_batch:
            out.add(base)
        else:
            best = 1
            for h in HCN_LIST:
                if base * h > max_batch:
                    break
                best = h
            out.add(base * best)
    return sorted(out)


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_gpus: int, max_gpus: int) -> List[int]:
    """All chip counts g in [min,max] such that batch_size = micro * k * g
    for some configured micro batch and integer gradient-accumulation k."""
    valid = set()
    for micro in micro_batches:
        if batch_size % micro:
            continue
        max_g = batch_size // micro
        if min_gpus <= max_g <= max_gpus:
            valid.add(max_g)
        for g in range(1, max_g // 2 + 1):
            if g > max_gpus:
                break
            if g < min_gpus:
                continue
            if max_g % g == 0:
                valid.add(g)
    return sorted(valid)


def _best_candidate(candidates, micro_batches, min_gpus, max_gpus,
                    prefer_larger) -> Tuple[int, List[int]]:
    best_batch = min(micro_batches)
    best_valid: List[int] = []
    for batch in candidates:
        valid = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        better = (len(valid) > len(best_valid)
                  or (len(valid) == len(best_valid)
                      and ((prefer_larger and batch > best_batch)
                           or (not prefer_larger and batch < best_batch))))
        if better:
            best_batch, best_valid = batch, valid
    return best_batch, best_valid


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                             min_gpus=None, max_gpus=None,
                             prefer_larger=True):
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    if not all(m <= max_acceptable_batch_size for m in micro_batches):
        raise ValueError("all micro batches must be ≤ "
                         f"max_train_batch_size={max_acceptable_batch_size}")
    bases = list(micro_batches) + [_lcm(micro_batches)]
    candidates = get_candidate_batch_sizes(bases, max_acceptable_batch_size)
    return _best_candidate(candidates, micro_batches, min_gpus, max_gpus,
                           prefer_larger)


def _get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size,
                             current_num_gpus, min_gpus=None, max_gpus=None,
                             prefer_larger=True, num_gpus_per_node=1,
                             model_parallel_size=1):
    """v0.2 works at host granularity and is MP-aware: the data-parallel
    world is chips/mp, and batch candidates are per-host multiples."""
    if num_gpus_per_node % model_parallel_size:
        raise ElasticityError(
            f"chips per host {num_gpus_per_node} must be divisible by "
            f"model_parallel_size {model_parallel_size}")
    dp_per_node = num_gpus_per_node // model_parallel_size

    def microbatch_for(batch):
        cand = None
        for m in micro_batches:
            if (batch // current_num_gpus) % m == 0:
                if cand is None or (prefer_larger and m > cand):
                    cand = m
        return cand

    batch, valid_nodes = _get_compatible_gpus_v01(
        micro_batches, int(max_acceptable_batch_size / dp_per_node),
        int(min_gpus / num_gpus_per_node) if min_gpus else None,
        int(max_gpus / num_gpus_per_node) if max_gpus else None,
        prefer_larger=prefer_larger)
    batch = int(batch) * dp_per_node
    valid_dp = [n * dp_per_node for n in valid_nodes]
    if current_num_gpus // model_parallel_size in valid_dp:
        return batch, valid_dp, microbatch_for(batch)

    # current world not covered: fall back to the best batch for exactly it
    current_dp = (current_num_gpus / num_gpus_per_node) * dp_per_node
    cands = [m * current_dp * math.floor(
        max_acceptable_batch_size / (m * current_dp))
        for m in micro_batches]
    batch = int(max(cands) if prefer_larger else min(cands))
    return batch, [int(current_dp)], microbatch_for(batch)


def elasticity_enabled(ds_config: dict) -> bool:
    return ds_config.get("elasticity", {}).get("enabled", False)


def ensure_immutable_elastic_config(runtime_elastic_config_dict: dict):
    """The scheduler computed resources from this config — it must not
    change at runtime (reference ``:254``)."""
    import os
    import json
    frozen = os.environ.get("DEEPSPEED_ELASTICITY_CONFIG")
    if frozen:
        if json.loads(frozen) != runtime_elastic_config_dict:
            raise ElasticityConfigError(
                "elastic config changed between scheduling and runtime")


def compute_elastic_config(ds_config: dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch=False):
    """Given an elastic config section, return (final_batch_size,
    valid_gpus[, micro_batch]) — deterministic for a given config
    (reference ``compute_elastic_config`` ``:287``)."""
    if not isinstance(ds_config, dict):
        raise ValueError("ds_config must be a dict")
    if "elasticity" not in ds_config:
        raise ElasticityConfigError("'elasticity' section missing")
    cfg = ElasticityConfig(ds_config["elasticity"])
    if not cfg.enabled:
        raise ElasticityConfigError("elasticity is disabled")
    if cfg.version > LATEST_ELASTICITY_VERSION:
        raise ElasticityError(f"unsupported elasticity version {cfg.version}")

    max_gpus = (cfg.max_gpus if cfg.max_gpus > 0
                else cfg.max_acceptable_batch_size // min(cfg.micro_batches))
    micro = None
    if cfg.version >= 0.2:
        import os
        if world_size:
            current = world_size
        elif str(os.environ.get("WORLD_SIZE", "")).isnumeric():
            current = int(os.environ["WORLD_SIZE"])
        else:
            raise ElasticityConfigError(
                "elasticity v0.2 needs WORLD_SIZE (argument or env) to "
                "compute a valid batch size")
        batch, valid, micro = _get_compatible_gpus_v02(
            cfg.micro_batches, cfg.max_acceptable_batch_size, current,
            min_gpus=cfg.min_gpus, max_gpus=max_gpus,
            prefer_larger=cfg.prefer_larger_batch_size,
            num_gpus_per_node=cfg.num_gpus_per_node,
            model_parallel_size=cfg.model_parallel_size)
    else:
        batch, valid = _get_compatible_gpus_v01(
            cfg.micro_batches, cfg.max_acceptable_batch_size,
            min_gpus=cfg.min_gpus, max_gpus=max_gpus,
            prefer_larger=cfg.prefer_larger_batch_size)

    if world_size:
        # v0.2's valid list is in data-parallel units (chips / mp)
        check = (world_size // cfg.model_parallel_size
                 if cfg.version >= 0.2 else world_size)
        if check not in valid:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} (dp={check}) not in valid set "
                f"{valid}")
    if return_microbatch:
        if micro is None:
            ws = world_size or max(valid)
            per_rank = batch // ws
            fits = [m for m in cfg.micro_batches if per_rank % m == 0]
            if not fits:
                raise ElasticityError(
                    f"no micro batch fits batch={batch} world={ws}")
            micro = max(fits) if cfg.prefer_larger_batch_size else min(fits)
        return batch, valid, micro
    return batch, valid
